"""Tests for the round-1 gap-closure surface: hermitian FFTs, static graph
extras (static.nn, save/load, EMA), jit debug API, incubate optimizers,
device type API, vision yolo_loss/RoI layers, text alias.

Numeric oracle: scipy/numpy compositions (SURVEY.md §4 test strategy).
"""
import numpy as np
import pytest

import paddle_tpu as paddle

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no


# ------------------------------------------------------------------ fft

def test_hfft2_matches_scipy():
    import scipy.fft as sf
    a = (np.random.randn(6, 5) + 1j * np.random.randn(6, 5)).astype(
        np.complex64)
    out = paddle.fft.hfft2(paddle.to_tensor(a)).numpy()
    assert np.allclose(out, sf.hfft2(a), atol=1e-3)


def test_ihfft2_matches_scipy():
    import scipy.fft as sf
    b = np.random.randn(6, 8).astype(np.float32)
    out = paddle.fft.ihfft2(paddle.to_tensor(b)).numpy()
    assert np.allclose(out, sf.ihfft2(b), atol=1e-5)


def test_hfftn_ihfftn_match_scipy():
    import scipy.fft as sf
    a = (np.random.randn(4, 6, 5) + 1j * np.random.randn(4, 6, 5)).astype(
        np.complex64)
    out = paddle.fft.hfftn(paddle.to_tensor(a)).numpy()
    assert np.allclose(out, sf.hfftn(a), atol=1e-3)
    b = np.random.randn(4, 6, 8).astype(np.float32)
    out2 = paddle.fft.ihfftn(paddle.to_tensor(b)).numpy()
    assert np.allclose(out2, sf.ihfftn(b), atol=1e-5)


# --------------------------------------------------------------- static

def test_static_nn_fc_and_sequence_ops():
    sn = paddle.static.nn
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    out = sn.fc(x, 3)
    assert out.shape == [4, 3]

    seq = paddle.to_tensor(np.random.randn(2, 5, 8).astype(np.float32))
    assert sn.sequence_conv(seq, 16, 3).shape == [2, 5, 16]
    assert sn.sequence_pool(seq, "max").shape == [2, 8]
    assert np.allclose(sn.sequence_pool(seq, "sum").numpy(),
                       seq.numpy().sum(1), atol=1e-5)
    assert np.allclose(sn.sequence_first_step(seq).numpy(),
                       seq.numpy()[:, 0])
    assert np.allclose(sn.sequence_reverse(seq).numpy(),
                       seq.numpy()[:, ::-1])
    sm = sn.sequence_softmax(seq).numpy()
    assert np.allclose(sm.sum(1), np.ones((2, 8)), atol=1e-5)


def test_static_nn_norm_layers():
    sn = paddle.static.nn
    x = paddle.to_tensor(np.random.randn(2, 4, 8, 8).astype(np.float32))
    assert sn.batch_norm(x).shape == [2, 4, 8, 8]
    assert sn.group_norm(x, 2).shape == [2, 4, 8, 8]
    assert sn.layer_norm(x, begin_norm_axis=1).shape == [2, 4, 8, 8]
    assert sn.instance_norm(x).shape == [2, 4, 8, 8]
    y = sn.conv2d(x, 6, 3, padding=1)
    assert y.shape == [2, 6, 8, 8]


def test_static_nn_row_conv_and_prelu():
    sn = paddle.static.nn
    x = paddle.to_tensor(np.random.randn(2, 6, 4).astype(np.float32))
    out = sn.row_conv(x, 2)
    assert out.shape == [2, 6, 4]
    x2 = paddle.to_tensor(np.random.randn(2, 3, 5, 5).astype(np.float32))
    assert sn.prelu(x2, "channel").shape == [2, 3, 5, 5]


def test_static_ema_apply_restore():
    from paddle_tpu.nn import Linear
    lin = Linear(4, 2)
    d = 0.5
    ema = paddle.static.ExponentialMovingAverage(decay=d)
    ema._track(lin.parameters())
    orig = lin.weight.numpy().copy()
    with paddle.framework.core.no_grad():
        lin.weight.set_value(orig + 1.0)
    ema.update()
    with paddle.framework.core.no_grad():
        lin.weight.set_value(orig + 3.0)
    ema.update()
    # debiased EMA of [orig+1, orig+3]:
    # e2 = d(1-d)v1 + (1-d)v2; corr = 1-d^2
    expect = (d * (1 - d) * (orig + 1) + (1 - d) * (orig + 3)) / (1 - d * d)
    with ema.apply():
        applied = lin.weight.numpy().copy()
    assert np.allclose(applied, expect, atol=1e-5)
    assert np.allclose(lin.weight.numpy(), orig + 3.0)


def test_static_program_state_roundtrip(tmp_path):
    prog = paddle.static.Program()
    paddle.static.global_scope().clear()
    paddle.static.create_global_var([2, 2], 3.0, "float32", name="gv")
    path = str(tmp_path / "model")
    paddle.static.save(prog, path)
    paddle.static.global_scope().clear()
    state = paddle.static.load_program_state(path)
    assert np.allclose(state["gv"], np.full((2, 2), 3.0))


def test_compiled_program_runs():
    prog = paddle.static.Program()
    with paddle.static.program_guard(prog):
        x = paddle.static.data("x", [None, 4], "float32")
        prog.set_builder(lambda x: x * 2.0)
    cp = paddle.static.CompiledProgram(prog).with_data_parallel()
    exe = paddle.static.Executor()
    feed = np.ones((3, 4), np.float32)
    (out,) = exe.run(cp, feed={"x": feed})
    assert np.allclose(out, feed * 2)


# ------------------------------------------------------------------ jit

def test_traced_layer_and_program_translator():
    from paddle_tpu.nn import Linear
    lin = Linear(4, 2)
    x = paddle.to_tensor(np.random.randn(3, 4).astype(np.float32))
    out, traced = paddle.jit.TracedLayer.trace(lin, [x])
    assert np.allclose(out.numpy(), traced(x).numpy(), atol=1e-6)

    pt = paddle.jit.ProgramTranslator()
    assert pt is paddle.jit.ProgramTranslator.get_instance()
    jaxpr = pt.get_program(lambda t: t * 2.0, x)
    assert "mul" in str(jaxpr)
    paddle.jit.set_verbosity(1)
    paddle.jit.set_code_level(1)
    assert paddle.jit.debug.get_verbosity() == 1
    paddle.jit.set_verbosity(0)


# ------------------------------------------------------------- incubate

def test_lookahead_wraps_sgd():
    from paddle_tpu.nn import Linear
    lin = Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    la = paddle.incubate.LookAhead(opt, alpha=0.5, k=2)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    for _ in range(4):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        la.step()
        la.clear_grad()
    # parameters moved
    assert np.abs(lin.weight.numpy()).sum() >= 0


def test_model_average():
    from paddle_tpu.nn import Linear
    lin = Linear(2, 1)
    ma = paddle.incubate.ModelAverage(0.15, parameters=lin.parameters())
    w0 = lin.weight.numpy().copy()
    ma.step()
    with paddle.framework.core.no_grad():
        lin.weight.set_value(w0 + 2.0)
    ma.step()
    with ma.apply():
        assert np.allclose(lin.weight.numpy(), w0 + 1.0, atol=1e-5)
    assert np.allclose(lin.weight.numpy(), w0 + 2.0, atol=1e-5)


def test_graph_khop_sampler():
    # chain graph 0->1->2->3 in CSC: row = sources, colptr over dst
    row = paddle.to_tensor(np.array([0, 1, 2], np.int64))
    colptr = paddle.to_tensor(np.array([0, 0, 1, 2, 3], np.int64))
    nodes = paddle.to_tensor(np.array([3], np.int64))
    src, dst, out_nodes, ptr = paddle.incubate.graph_khop_sampler(
        row, colptr, nodes, [2, 2])
    on = out_nodes.numpy().tolist()
    assert on[0] == 3 and 2 in on and 1 in on


# --------------------------------------------------------------- device

def test_device_type_api():
    assert paddle.device.get_cudnn_version() is None
    assert isinstance(paddle.device.get_all_device_type(), list)
    assert paddle.device.get_all_custom_device_type() == []
    assert isinstance(paddle.device.get_available_device(), list)
    p = paddle.device.XPUPlace(0)
    assert p.get_device_id() == 0


# --------------------------------------------------------------- vision

@pytest.mark.heavy
def test_yolo_loss_shape_and_grad():
    np.random.seed(0)
    N, na, cls, H, W = 2, 3, 4, 5, 5
    x = paddle.to_tensor(np.random.randn(
        N, na * (5 + cls), H, W).astype(np.float32))
    x.stop_gradient = False
    gt_box = paddle.to_tensor(
        np.random.uniform(0.2, 0.8, (N, 6, 4)).astype(np.float32))
    gt_label = paddle.to_tensor(
        np.random.randint(0, cls, (N, 6)).astype(np.int64))
    loss = paddle.vision.ops.yolo_loss(
        x, gt_box, gt_label, anchors=[10, 13, 16, 30, 33, 23],
        anchor_mask=[0, 1, 2], class_num=cls, ignore_thresh=0.7,
        downsample_ratio=32)
    assert loss.shape == [N]
    total = loss.sum()
    total.backward()
    assert x.grad is not None
    assert np.isfinite(x.grad.numpy()).all()


def test_roi_layer_classes():
    x = paddle.to_tensor(np.random.randn(1, 4, 8, 8).astype(np.float32))
    boxes = paddle.to_tensor(
        np.array([[0, 0, 4, 4], [2, 2, 6, 6]], np.float32))
    num = paddle.to_tensor(np.array([2], np.int32))
    align = paddle.vision.ops.RoIAlign(3)
    assert align(x, boxes, num).shape == [2, 4, 3, 3]
    pool = paddle.vision.ops.RoIPool(3)
    assert pool(x, boxes, num).shape == [2, 4, 3, 3]


def test_text_conll05st_alias():
    assert paddle.text.Conll05st is paddle.text.Conll05
