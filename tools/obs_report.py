#!/usr/bin/env python
"""obs_report — the one-command answer to "what happened in this run".

Renders a human summary from a paddle_tpu metrics JSONL file (the
PADDLE_TPU_METRICS_FILE export — docs/OBSERVABILITY.md): training step
rollup (+ measured device time when the probe sampled), the compile
ledger per executable, the serving SLO/goodput rollup, the front-door
routing section (per-engine placements, handoffs, fleet SLO), the
cross-engine journey section (kind:"journey" phase splits + the
journey-vs-request-pair token reconciliation), the fleet snapshot /
load-harness section, the device-memory ledger section (kind:"memory"
per-tag peaks + attribution MISMATCH lines), the
distributed
observatory's collective top-k by wall time and per-rank skew table,
every anomaly event (stragglers, spikes, retraces, NaNs) in order, and
the static-analysis findings section (kind:"lint" — paddlelint).

Plain json + arithmetic — no framework import, so it runs anywhere the
JSONL landed (a laptop holding a pulled rank log included).

Usage: python tools/obs_report.py METRICS.jsonl [--top N]
Exit 0 on a rendered report, 2 on unreadable input.
"""
import argparse
import json
import sys


def load_records(path):
    recs = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # a torn tail line must not kill the report
            if isinstance(rec, dict):
                recs.append(rec)
    return recs


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _fmt_s(v):
    if v >= 1.0:
        return f"{v:.2f}s"
    return f"{v * 1e3:.2f}ms"


def section_steps(recs, out):
    steps = [r for r in recs if r.get("kind") == "step"]
    scans = [r for r in recs if r.get("kind") == "scan"]
    if not steps and not scans:
        return
    out.append("== training ==")
    if steps:
        times = sorted(float(r.get("step_time_s", 0.0)) for r in steps)
        compile_s = sum(float(r.get("compile_s", 0.0)) for r in steps)
        mfus = [float(r.get("mfu", 0.0)) for r in steps
                if r.get("mfu", 0.0)]
        out.append(
            f"  {len(steps)} steps  wall {sum(times):.2f}s  "
            f"p50 {_fmt_s(_pct(times, 50))}  p99 {_fmt_s(_pct(times, 99))}"
            f"  compile {compile_s:.2f}s")
        if mfus:
            out.append(f"  mfu (cost analysis, last): {mfus[-1]:.4f}")
        probes = [r for r in steps if "step_time_device_s" in r]
        if probes:
            dts = sorted(float(r["step_time_device_s"]) for r in probes)
            mm = [float(r.get("mfu_measured", 0.0)) for r in probes]
            ov = [float(r.get("overlap_fraction", 0.0)) for r in probes]
            out.append(
                f"  measured device time ({len(probes)} probes): "
                f"p50 {_fmt_s(_pct(dts, 50))}  "
                f"mfu_measured {_pct(sorted(mm), 50):.4f}  "
                f"overlap {_pct(sorted(ov), 50):.3f}")
    if scans:
        n = sum(int(r.get("steps", 0)) for r in scans)
        out.append(f"  {len(scans)} scanned segments ({n} steps)")
    out.append("")


def section_compiles(recs, out, top):
    comps = [r for r in recs if r.get("kind") == "compile"]
    if not comps:
        return
    by_tag = {}
    for r in comps:
        t = by_tag.setdefault(r.get("tag", "?"),
                              {"n": 0, "s": 0.0, "hits": 0})
        t["n"] += 1
        t["s"] += float(r.get("lower_s", 0.0)) + \
            float(r.get("compile_s", 0.0))
        t["hits"] += 1 if r.get("cache_hit") else 0
    out.append(f"== compiles ==  ({len(comps)} records, "
               f"{sum(t['s'] for t in by_tag.values()):.2f}s total)")
    rows = sorted(by_tag.items(), key=lambda kv: -kv[1]["s"])[:top]
    for tag, t in rows:
        out.append(f"  {tag:<28} {t['s']:>8.2f}s  "
                   f"x{t['n']}  cache hits {t['hits']}/{t['n']}")
    out.append("")


def section_serve(recs, out):
    reqs = [r for r in recs if r.get("kind") == "request"]
    if not reqs:
        return
    outcomes = {}
    for r in reqs:
        outcomes[r.get("outcome", "?")] = \
            outcomes.get(r.get("outcome", "?"), 0) + 1
    # a "handoff" record is the NON-terminal prefill half of a
    # disaggregated pair — its tokens are re-counted by the decode-side
    # record (seeded at adoption), so it stays out of the token math
    gen = sum(int(r.get("generated_tokens", 0)) for r in reqs
              if r.get("outcome") != "handoff")
    good = sum(int(r.get("generated_tokens", 0)) for r in reqs
               if r.get("outcome") == "completed")
    dl = [r for r in reqs if "deadline_met" in r]
    met = sum(1 for r in dl if r.get("deadline_met"))
    lats = sorted(float(r.get("latency_s", 0.0)) for r in reqs)
    out.append(f"== serving ==  ({len(reqs)} requests)")
    out.append("  outcomes: " + "  ".join(
        f"{k}={v}" for k, v in sorted(outcomes.items())))
    # cache strategy split: legacy records predate the field and are
    # paged by construction, so an all-paged ledger stays as before
    by_strat = {}
    for r in reqs:
        s = r.get("cache_strategy", "paged")
        t = by_strat.setdefault(s, {"n": 0, "engines": set()})
        t["n"] += 1
        t["engines"].add(r.get("engine", "?"))
    if set(by_strat) != {"paged"}:
        out.append("  cache strategies: " + "  ".join(
            f"{s}={t['n']} ({len(t['engines'])} engine"
            f"{'s' if len(t['engines']) != 1 else ''})"
            for s, t in sorted(by_strat.items())))
    out.append(f"  latency p50 {_fmt_s(_pct(lats, 50))}  "
               f"p99 {_fmt_s(_pct(lats, 99))}")
    waste = gen - good
    out.append(f"  tokens: goodput {good}  wasted {waste}")
    if dl:
        out.append(f"  slo attainment: {met}/{len(dl)} "
                   f"({met / len(dl):.3f})")
    out.append("")


def section_routing(recs, out):
    """The serving front door (kind:"route" — ServingRouter,
    paddle_tpu/inference/frontdoor.py): per-engine placement counts by
    SLO class, prefill->decode handoffs with the pages they moved,
    rejections, and the fleet SLO rollup joined from the request
    ledger (deadline attainment per engine)."""
    routes = [r for r in recs if r.get("kind") == "route"]
    if not routes:
        return
    disp = [r for r in routes if r.get("outcome") == "dispatched"]
    hoffs = [r for r in routes if r.get("outcome") == "handoff"]
    rej = [r for r in routes if r.get("outcome") == "rejected"]
    out.append(f"== routing ==  ({len(routes)} decisions: "
               f"{len(disp)} dispatched, {len(hoffs)} handoffs, "
               f"{len(rej)} rejected)")
    by_engine = {}
    for r in disp:
        e = by_engine.setdefault(r.get("engine", "?"),
                                 {"n": 0, "cls": {}, "aff": 0})
        e["n"] += 1
        cls = r.get("slo_class", "?")
        e["cls"][cls] = e["cls"].get(cls, 0) + 1
        e["aff"] += 1 if r.get("prefix_affinity") else 0
    for name in sorted(by_engine):
        e = by_engine[name]
        cls_txt = "  ".join(f"{k}={v}" for k, v in sorted(
            e["cls"].items()))
        out.append(f"  {name:<24} {e['n']:>4} placed  [{cls_txt}]"
                   f"  prefix-affinity {e['aff']}")
    if hoffs:
        pairs = {}
        for r in hoffs:
            key = (r.get("from_engine", "?"), r.get("engine", "?"))
            p = pairs.setdefault(key, {"n": 0, "pages": 0, "toks": 0,
                                       "sbytes": 0})
            p["n"] += 1
            p["pages"] += int(r.get("pages_moved", 0))
            p["toks"] += int(r.get("chain_tokens", 0))
            p["sbytes"] += int(r.get("state_bytes", 0))
        for (src, dst), p in sorted(pairs.items()):
            # a recurrent handoff moves zero pages — its payload is the
            # fixed-size state blob, so show the bytes when they exist
            sb = f"  {p['sbytes']} state bytes" if p["sbytes"] else ""
            out.append(f"  handoff {src} -> {dst}: x{p['n']}  "
                       f"{p['pages']} pages  {p['toks']} kv tokens{sb}")
    # fleet SLO rollup: join the request ledger per placed engine
    reqs = [r for r in recs if r.get("kind") == "request"
            and "deadline_met" in r]
    if reqs:
        by_eng = {}
        for r in reqs:
            b = by_eng.setdefault(r.get("engine", "?"), [0, 0])
            b[0] += 1 if r.get("deadline_met") else 0
            b[1] += 1
        met = sum(b[0] for b in by_eng.values())
        total = sum(b[1] for b in by_eng.values())
        per = "  ".join(f"{k}={b[0]}/{b[1]}"
                        for k, b in sorted(by_eng.items()))
        out.append(f"  fleet slo: {met}/{total} "
                   f"({met / total:.3f})  [{per}]")
    out.append("")


def section_journeys(recs, out):
    """Cross-engine request journeys (kind:"journey" — the fleet
    observatory, profiler/fleet_observatory.py): the phase split of
    every handed-off request, per prefill->decode pair, plus the
    reconciliation of each journey against its TWO request records
    (joined on request_id, cross-named by handoff_of) — a pair whose
    token counts disagree means the adoption seeding lied."""
    js = [r for r in recs if r.get("kind") == "journey"]
    if not js:
        return
    gaps = sorted(float(r.get("handoff_gap_s", 0.0)) for r in js)
    lats = sorted(float(r.get("latency_s", 0.0)) for r in js)
    out.append(f"== journeys ==  ({len(js)} handed-off requests)")
    out.append(f"  latency p50 {_fmt_s(_pct(lats, 50))}  "
               f"p99 {_fmt_s(_pct(lats, 99))}  handoff gap p50 "
               f"{_fmt_s(_pct(gaps, 50))}  p99 {_fmt_s(_pct(gaps, 99))}")
    for key in ("queue_s", "prefill_s", "handoff_gap_s", "decode_s"):
        vals = sorted(float(r.get(key, 0.0)) for r in js)
        out.append(f"  {key:<14} p50 {_fmt_s(_pct(vals, 50))}")
    pairs = {}
    for r in js:
        key = (r.get("prefill_engine", "?"), r.get("decode_engine", "?"))
        p = pairs.setdefault(key, {"n": 0, "pages": 0, "met": 0,
                                   "dl": 0})
        p["n"] += 1
        p["pages"] += int(r.get("pages_moved", 0))
        if "deadline_met" in r:
            p["dl"] += 1
            p["met"] += 1 if r.get("deadline_met") else 0
    for (src, dst), p in sorted(pairs.items()):
        slo = f"  slo {p['met']}/{p['dl']}" if p["dl"] else ""
        out.append(f"  {src} -> {dst}: x{p['n']}  "
                   f"{p['pages']} pages{slo}")
    # pair reconciliation: journey vs its two request records
    by_rid = {}
    for r in recs:
        if r.get("kind") == "request" and r.get("request_id"):
            by_rid.setdefault(r["request_id"], []).append(r)
    ok, bad = 0, []
    for j in js:
        rid = j.get("request_id")
        sides = by_rid.get(rid, [])
        pre = [r for r in sides if r.get("outcome") == "handoff"
               and r.get("engine") == j.get("prefill_engine")]
        dec = [r for r in sides if r.get("outcome") != "handoff"
               and r.get("engine") == j.get("decode_engine")]
        if len(pre) != 1 or len(dec) != 1:
            bad.append(f"{rid}: {len(pre)} prefill / {len(dec)} decode "
                       "record(s), expected 1+1")
            continue
        p, d = pre[0], dec[0]
        pgen = int(p.get("generated_tokens", 0))
        dgen = int(d.get("generated_tokens", 0))
        if p.get("handoff_of") != j.get("decode_engine") or \
                d.get("handoff_of") != j.get("prefill_engine"):
            bad.append(f"{rid}: handoff_of cross-naming broken "
                       f"({p.get('handoff_of')!r} / "
                       f"{d.get('handoff_of')!r})")
        elif dgen < pgen or dgen != int(j.get("generated_tokens", 0)):
            bad.append(
                f"{rid}: tokens do not reconcile (prefill {pgen}, "
                f"decode {dgen}, journey "
                f"{j.get('generated_tokens')}) — the decode side is "
                "seeded with the prefill tokens and must carry the "
                "journey total")
        else:
            ok += 1
    out.append(f"  pair reconciliation: {ok}/{len(js)} journeys "
               "match their request-record pairs")
    for msg in bad[:5]:
        out.append(f"  MISMATCH {msg}")
    out.append("")


def section_fleet(recs, out):
    """Fleet snapshots (kind:"fleet") + load-harness summaries
    (kind:"harness"): the latest per-router snapshot's load and rates,
    and each harness run's goodput/SLO line."""
    fleets = [r for r in recs if r.get("kind") == "fleet"]
    harness = [r for r in recs if r.get("kind") == "harness"]
    if not fleets and not harness:
        return
    out.append(f"== fleet ==  ({len(fleets)} snapshot(s), "
               f"{len(harness)} harness run(s))")
    latest = {}
    for r in fleets:
        latest[r.get("router", "?")] = r  # file order: last wins
    for name in sorted(latest):
        r = latest[name]
        sat = r.get("saturated") or []
        sat_txt = f"  SATURATED {sat}" if sat else ""
        out.append(
            f"  {name}: {r.get('n_engines', '?')} engines / "
            f"{r.get('n_pools', '?')} pool(s)  queue "
            f"{r.get('queue_depth', 0)}  active {r.get('active', 0)}  "
            f"claims {r.get('outstanding_claims', 0)}{sat_txt}")
        out.append(
            f"    rates/s: in {r.get('arrival_rate', 0)}  done "
            f"{r.get('completion_rate', 0)}  handoff "
            f"{r.get('handoff_rate', 0)}  reject "
            f"{r.get('rejection_rate', 0)}")
        att = r.get("slo_attainment") or {}
        if att:
            out.append("    slo attainment: " + "  ".join(
                f"{k}={v:.3f}" for k, v in sorted(att.items())))
    for r in harness:
        out.append(
            f"  harness seed={r.get('seed', '?')} "
            f"{r.get('requests', '?')} reqs in "
            f"{float(r.get('duration_s', 0.0)):.1f}s: goodput "
            f"{float(r.get('goodput_tokens_per_s', 0.0)):.1f} tok/s  "
            f"ttft p50 {_fmt_s(float(r.get('ttft_p50_s', 0.0)))} "
            f"p99 {_fmt_s(float(r.get('ttft_p99_s', 0.0)))}  rejected "
            f"{float(r.get('rejected_fraction', 0.0)):.3f}  expired "
            f"{float(r.get('expired_fraction', 0.0)):.3f}  peak "
            f"in-flight {r.get('peak_in_flight', '?')}")
    out.append("")


def section_collectives(recs, out, top):
    colls = [r for r in recs if r.get("kind") == "collective"]
    if not colls:
        return
    by_op = {}
    for r in colls:
        t = by_op.setdefault(r.get("op", "?"),
                             {"n": 0, "s": 0.0, "b": 0, "bw": []})
        t["n"] += 1
        t["s"] += float(r.get("wall_s", 0.0))
        t["b"] += int(r.get("bytes", 0))
        bw = float(r.get("bw_gbps", 0.0))
        if bw > 0:
            t["bw"].append(bw)
    out.append(f"== collectives ==  ({len(colls)} sampled records; "
               f"top {top} by sampled wall time)")
    rows = sorted(by_op.items(), key=lambda kv: -kv[1]["s"])[:top]
    for op, t in rows:
        bw = sorted(t["bw"])
        bw_txt = f"  bw p50 {_pct(bw, 50):.2f} GB/s" if bw else ""
        out.append(f"  {op:<16} {t['s'] * 1e3:>9.3f}ms sampled  "
                   f"x{t['n']}  {t['b']} bytes{bw_txt}")
    out.append("")


def section_ranks(recs, out):
    rstats = [r for r in recs if r.get("kind") == "rankstat"]
    if not rstats:
        return
    latest = {}
    for r in rstats:
        latest[r.get("rank", 0)] = r  # file order: last wins
    out.append(f"== ranks ==  ({len(rstats)} rankstat records, "
               f"{len(latest)} rank(s))")
    for rank in sorted(latest):
        r = latest[rank]
        out.append(
            f"  rank {rank}: step p50 "
            f"{_fmt_s(float(r.get('step_time_p50_s', 0.0)))}  "
            f"p99 {_fmt_s(float(r.get('step_time_p99_s', 0.0)))}  "
            f"coll wait {float(r.get('collective_wait_share', 0.0)):.3f}"
            f"  blocked {_fmt_s(float(r.get('host_blocked_s', 0.0)))}  "
            f"clock {float(r.get('clock_offset_s', 0.0)) * 1e3:+.1f}ms")
    out.append("")


def _fmt_bytes(v):
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024.0 or unit == "GiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024.0


def section_memory(recs, out):
    """Device-memory ledger rollup (kind:"memory" —
    profiler/mem_observatory.py): per-tag peak bytes across the run's
    records, the last record's attribution split, and a MISMATCH line
    whenever a measured record's unattributed bytes exceed what the
    compile ledger's executable peaks can explain — the leak signature
    the memory observatory exists to surface."""
    mems = [r for r in recs if r.get("kind") == "memory"]
    if not mems:
        return
    sources = {}
    for r in mems:
        sources[r.get("source", "?")] = sources.get(
            r.get("source", "?"), 0) + 1
    out.append(f"== memory ==  ({len(mems)} records: " + "  ".join(
        f"{k}={v}" for k, v in sorted(sources.items())) + ")")
    peaks = {}
    for r in mems:
        for tag, b in (r.get("tags") or {}).items():
            if isinstance(b, (int, float)) and not isinstance(b, bool):
                peaks[tag] = max(peaks.get(tag, 0), int(b))
    for tag, b in sorted(peaks.items(), key=lambda kv: -kv[1]):
        out.append(f"  {tag:<28} peak {_fmt_bytes(b):>10}")
    last = mems[-1]
    out.append(
        f"  last: attributed {_fmt_bytes(last.get('attributed_bytes', 0))}"
        f"  unattributed {_fmt_bytes(last.get('unattributed_bytes', 0))}"
        f"  in_use {_fmt_bytes(last.get('device_bytes_in_use', 0))}"
        f"  measured={bool(last.get('measured'))}")
    frags = [float(r.get("fragmentation", 0.0)) for r in mems
             if "fragmentation" in r]
    if frags:
        out.append(f"  kv fragmentation: last {frags[-1]:.3f}  "
                   f"max {max(frags):.3f}")
    # a measured record whose unattributed bytes exceed the compile
    # ledger's executable peaks (plus 10%-of-device or 1 MiB slack)
    # points at memory NO tag or executable explains
    for r in mems:
        if not r.get("measured"):
            continue
        unattr = int(r.get("unattributed_bytes", 0))
        bound = int(r.get("executable_peak_bytes", 0))
        tol = max(int(0.10 * int(r.get("device_bytes_in_use", 0))),
                  1 << 20)
        if unattr > bound + tol:
            out.append(
                f"  MISMATCH at {r.get('source', '?')} step "
                f"{r.get('step', '?')}: unattributed "
                f"{_fmt_bytes(unattr)} exceeds executable peaks "
                f"{_fmt_bytes(bound)} (+{_fmt_bytes(tol)} tolerance)")
    out.append("")


def section_events(recs, out, top):
    evs = [r for r in recs if r.get("kind") == "event"]
    if not evs:
        return
    stragglers = [e for e in evs if e.get("event") == "straggler"]
    out.append(f"== events ==  ({len(evs)} total, "
               f"{len(stragglers)} straggler(s))")
    for e in stragglers:
        out.append(
            f"  STRAGGLER rank {e.get('straggler_rank', '?')} at step "
            f"{e.get('step', '?')}: "
            f"{_fmt_s(float(e.get('step_time_s', 0.0)))} vs median "
            f"{_fmt_s(float(e.get('median_s', 0.0)))} "
            f"(lag {_fmt_s(float(e.get('lag_s', 0.0)))})")
    others = [e for e in evs if e.get("event") != "straggler"]
    counts = {}
    for e in others:
        counts[e.get("event", "?")] = counts.get(e.get("event", "?"), 0) + 1
    if counts:
        out.append("  other: " + "  ".join(
            f"{k}={v}" for k, v in sorted(counts.items())))
    out.append("")


def section_lint(recs, out, top):
    """Static-analysis findings (kind:"lint" — tools/paddlelint.py,
    docs/STATIC_ANALYSIS.md): unsuppressed findings are the headline
    (a clean run renders none), suppressions roll up per pass."""
    lints = [r for r in recs if r.get("kind") == "lint"]
    if not lints:
        return
    live = [r for r in lints if not r.get("suppressed")]
    sup = [r for r in lints if r.get("suppressed")]
    out.append(f"== lint ==  ({len(live)} finding(s), {len(sup)} "
               "suppressed with reasons)")
    for r in live[:max(top, 5)]:
        out.append(
            f"  {r.get('severity', '?').upper()} "
            f"[{r.get('pass', '?')}/{r.get('rule', '?')}] "
            f"{r.get('file', '?')}:{r.get('line', '?')} "
            f"{str(r.get('message', ''))[:100]}")
    if len(live) > max(top, 5):
        out.append(f"  ... and {len(live) - max(top, 5)} more")
    by_pass = {}
    for r in sup:
        by_pass[r.get("pass", "?")] = by_pass.get(r.get("pass", "?"),
                                                  0) + 1
    if by_pass:
        out.append("  suppressed: " + "  ".join(
            f"{k}={v}" for k, v in sorted(by_pass.items())))
    out.append("")


def render(recs, top=5):
    out = []
    ranks = sorted({r.get("rank", 0) for r in recs})
    kinds = {}
    for r in recs:
        kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"), 0) + 1
    out.append(f"run summary: {len(recs)} records, rank(s) "
               f"{','.join(str(r) for r in ranks)}  [" + "  ".join(
                   f"{k}:{v}" for k, v in sorted(kinds.items())) + "]")
    out.append("")
    section_steps(recs, out)
    section_compiles(recs, out, top)
    section_serve(recs, out)
    section_routing(recs, out)
    section_journeys(recs, out)
    section_fleet(recs, out)
    section_memory(recs, out)
    section_collectives(recs, out, top)
    section_ranks(recs, out)
    section_events(recs, out, top)
    section_lint(recs, out, top)
    return "\n".join(out).rstrip() + "\n"


def main(argv=None):
    ap = argparse.ArgumentParser(
        "obs_report", description="human run summary from a paddle_tpu "
                                  "metrics JSONL")
    ap.add_argument("files", nargs="+", help="metrics JSONL file(s) — "
                    "several rank files render as one run")
    ap.add_argument("--top", type=int, default=5,
                    help="rows per top-k table (default 5)")
    args = ap.parse_args(argv)
    recs = []
    for path in args.files:
        try:
            recs.extend(load_records(path))
        except OSError as e:
            print(f"obs_report: {e}", file=sys.stderr)
            return 2
    if not recs:
        print("obs_report: no records in input", file=sys.stderr)
        return 2
    sys.stdout.write(render(recs, top=args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
