"""Detection ops. Parity: python/paddle/vision/ops.py (CUDA kernels in the
reference, e.g. paddle/fluid/operators/detection/). Implemented as pure
jnp compositions — gather/where formulations that XLA vectorizes; nms runs
as a host-side numpy routine (dynamic output size, like the reference's
CPU kernel)."""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "yolo_box",
           "prior_box", "deform_conv2d", "DeformConv2D", "distribute_fpn_proposals",
           "generate_proposals", "read_file", "decode_jpeg", "psroi_pool"]


@jax.jit
def _nms_keep_mask(bs, thresh):
    """Greedy suppression over score-sorted boxes [N, 4]: a fori_loop
    where step i suppresses every later box with IoU(i, ·) > thresh in one
    O(N) vector op — no [N, N] matrix, no host loop. Returns keep mask in
    sorted order. Replaces the host O(n^2) python loop (ref CPU kernel:
    paddle/fluid/operators/detection/nms_op.cc)."""
    N = bs.shape[0]
    areas = (bs[:, 2] - bs[:, 0]) * (bs[:, 3] - bs[:, 1])
    idx = jnp.arange(N)

    def body(i, keep):
        bi = bs[i]
        xx1 = jnp.maximum(bi[0], bs[:, 0])
        yy1 = jnp.maximum(bi[1], bs[:, 1])
        xx2 = jnp.minimum(bi[2], bs[:, 2])
        yy2 = jnp.minimum(bi[3], bs[:, 3])
        inter = jnp.maximum(0.0, xx2 - xx1) * jnp.maximum(0.0, yy2 - yy1)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        sup = (iou > thresh) & keep[i] & (idx > i)
        return keep & ~sup

    return jax.lax.fori_loop(0, N, body, jnp.ones((N,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    b = boxes.value.astype(jnp.float32)
    N = int(b.shape[0])
    if N == 0:
        return Tensor(jnp.zeros((0,), jnp.int64))
    s = scores.value.astype(jnp.float32) if scores is not None \
        else jnp.ones((N,), jnp.float32)
    if category_idxs is not None:
        # shift each category onto a disjoint coordinate island so one
        # suppression pass never crosses categories (IoU across islands=0)
        c = category_idxs.value.astype(jnp.float32)
        span = jnp.max(b) - jnp.min(b) + 2.0
        b = b + (c * span)[:, None]
    # pad to a multiple of 256 with far-away zero-area boxes so the jitted
    # suppression loop compiles once per size bucket, not once per N
    Np = -(-N // 256) * 256
    if Np != N:
        pad_box = jnp.full((Np - N, 4), jnp.max(b) + 1e6)  # zero-area
        b = jnp.concatenate([b, pad_box], axis=0)
        s = jnp.concatenate([s, jnp.full((Np - N,), -jnp.inf)], axis=0)
    order = jnp.argsort(-s)
    keep = _nms_keep_mask(b[order], jnp.float32(iou_threshold))
    # dynamic-size result: one host sync at the end (like the reference's
    # CPU kernel output), all O(N^2) work stayed on device
    order_np = np.asarray(order)
    kept = order_np[np.asarray(keep)]
    kept = kept[kept < N]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(kept.astype(np.int64))


def _roi_image_index(n_rois, rois_num):
    """Batch-image index per RoI from per-image counts. Works under jit:
    roi r belongs to the first image whose cumulative count exceeds r."""
    cum = jnp.cumsum(jnp.asarray(rois_num))
    return jnp.sum(jnp.arange(n_rois)[:, None] >= cum[None, :],
                   axis=1).astype(jnp.int32)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def fn(feat, rois, rois_num):
        N, C, H, W = feat.shape
        img_idx = _roi_image_index(rois.shape[0], rois_num)

        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1e-5)
        rh = jnp.maximum(y2 - y1, 1e-5)
        bw = rw / ow
        bh = rh / oh
        sr = sampling_ratio if sampling_ratio > 0 else 2

        ys = y1[:, None, None] + (jnp.arange(oh)[None, :, None] +
                                  (jnp.arange(sr)[None, None, :] + 0.5)
                                  / sr) * bh[:, None, None]
        xs = x1[:, None, None] + (jnp.arange(ow)[None, :, None] +
                                  (jnp.arange(sr)[None, None, :] + 0.5)
                                  / sr) * bw[:, None, None]

        def bilinear(img, yy, xx):
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            wy = yy - y0
            wx = xx - x0
            v = (img[:, y0, x0] * (1 - wy) * (1 - wx) +
                 img[:, y1_, x0] * wy * (1 - wx) +
                 img[:, y0, x1_] * (1 - wy) * wx +
                 img[:, y1_, x1_] * wy * wx)
            return v

        def one_roi(ridx):
            img = feat[img_idx[ridx]]
            yy = ys[ridx]      # [oh, sr]
            xx = xs[ridx]      # [ow, sr]
            gy = jnp.broadcast_to(yy[:, None, :, None], (oh, ow, sr, sr))
            gx = jnp.broadcast_to(xx[None, :, None, :], (oh, ow, sr, sr))
            vals = bilinear(img, gy.reshape(-1), gx.reshape(-1))
            vals = vals.reshape(C, oh, ow, sr * sr)
            return vals.mean(-1)

        return jax.vmap(one_roi)(jnp.arange(rois.shape[0]))
    return apply_op(fn, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     sampling_ratio=2, aligned=False)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    def fn(pb, pbv, tb):
        pw = pb[:, 2] - pb[:, 0] + (0 if box_normalized else 1)
        ph = pb[:, 3] - pb[:, 1] + (0 if box_normalized else 1)
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + (0 if box_normalized else 1)
            th = tb[:, 3] - tb[:, 1] + (0 if box_normalized else 1)
            tcx = tb[:, 0] + tw / 2
            tcy = tb[:, 1] + th / 2
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                             jnp.log(tw / pw), jnp.log(th / ph)], -1)
            return out / pbv
        # decode
        d = tb * pbv
        ocx = d[..., 0] * pw + pcx
        ocy = d[..., 1] * ph + pcy
        ow = jnp.exp(d[..., 2]) * pw
        oh = jnp.exp(d[..., 3]) * ph
        return jnp.stack([ocx - ow / 2, ocy - oh / 2,
                          ocx + ow / 2, ocy + oh / 2], -1)
    return apply_op(fn, prior_box, prior_box_var, target_box)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    na = len(anchors) // 2

    def fn(feat, imsz):
        N, C, H, W = feat.shape
        feat = feat.reshape(N, na, 5 + class_num, H, W)
        gx = jnp.arange(W).reshape(1, 1, 1, W)
        gy = jnp.arange(H).reshape(1, 1, H, 1)
        aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, na, 1, 1)
        ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, na, 1, 1)
        sig = jax.nn.sigmoid
        bx = (sig(feat[:, :, 0]) * scale_x_y -
              (scale_x_y - 1) / 2 + gx) / W
        by = (sig(feat[:, :, 1]) * scale_x_y -
              (scale_x_y - 1) / 2 + gy) / H
        bw = jnp.exp(feat[:, :, 2]) * aw / (W * downsample_ratio)
        bh = jnp.exp(feat[:, :, 3]) * ah / (H * downsample_ratio)
        conf = sig(feat[:, :, 4])
        probs = sig(feat[:, :, 5:]) * conf[:, :, None]
        imh = imsz[:, 0].reshape(N, 1, 1, 1).astype(jnp.float32)
        imw = imsz[:, 1].reshape(N, 1, 1, 1).astype(jnp.float32)
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
        mask = (conf > conf_thresh).reshape(N, -1, 1)
        boxes = boxes * mask
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
        return boxes, scores
    return apply_op(fn, x, img_size)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0., 0.), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    def fn(feat, img):
        H, W = feat.shape[2], feat.shape[3]
        IH, IW = img.shape[2], img.shape[3]
        sh = steps[1] or IH / H
        sw = steps[0] or IW / W
        ars = list(aspect_ratios)
        if flip:
            ars = ars + [1.0 / a for a in ars if a != 1.0]
        boxes = []
        for ms in min_sizes:
            for ar in ars:
                bw = ms * np.sqrt(ar) / 2
                bh = ms / np.sqrt(ar) / 2
                boxes.append((bw, bh))
            if max_sizes:
                for mx in max_sizes:
                    s = np.sqrt(ms * mx) / 2
                    boxes.append((s, s))
        nb = len(boxes)
        cx = (jnp.arange(W) + offset) * sw
        cy = (jnp.arange(H) + offset) * sh
        gcx, gcy = jnp.meshgrid(cx, cy, indexing="xy")
        out = []
        for bw, bh in boxes:
            b = jnp.stack([(gcx - bw) / IW, (gcy - bh) / IH,
                           (gcx + bw) / IW, (gcy + bh) / IH], -1)
            out.append(b)
        pri = jnp.stack(out, 2)  # H,W,nb,4
        if clip:
            pri = jnp.clip(pri, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               pri.shape)
        return pri, var
    return apply_op(fn, input, image)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 as gather + matmul (reference:
    paddle/fluid/operators/deformable_conv_op.cu)."""
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def fn(a, off, w, *rest):
        N, C, H, W = a.shape
        OC, ICg, KH, KW = w.shape
        OH = (H + 2 * pd[0] - dl[0] * (KH - 1) - 1) // st[0] + 1
        OW = (W + 2 * pd[1] - dl[1] * (KW - 1) - 1) // st[1] + 1
        base_y = (jnp.arange(OH) * st[0] - pd[0])[:, None, None]
        base_x = (jnp.arange(OW) * st[1] - pd[1])[None, :, None]
        ky = (jnp.arange(KH) * dl[0])[None, None, :, None]
        kx = (jnp.arange(KW) * dl[1])[None, None, None, :]
        off = off.reshape(N, deformable_groups, 2, KH, KW, OH, OW)
        m = None
        idx_r = 0
        if mask is not None:
            m = rest[idx_r].reshape(N, deformable_groups, KH, KW, OH, OW)
            idx_r += 1
        # sampling positions: [N, dg, KH, KW, OH, OW]
        pos_y = (jnp.arange(OH) * st[0] - pd[0]).reshape(1, 1, 1, 1, OH, 1) \
            + (jnp.arange(KH) * dl[0]).reshape(1, 1, KH, 1, 1, 1) \
            + off[:, :, 0]
        pos_x = (jnp.arange(OW) * st[1] - pd[1]).reshape(1, 1, 1, 1, 1, OW) \
            + (jnp.arange(KW) * dl[1]).reshape(1, 1, 1, KW, 1, 1) \
            + off[:, :, 1]

        y0 = jnp.floor(pos_y)
        x0 = jnp.floor(pos_x)
        wy = pos_y - y0
        wx = pos_x - x0

        def gather(img_dg, yy, xx):
            # img_dg: [Cg, H, W]; yy/xx: [...]
            yi = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
            valid = ((yy >= 0) & (yy <= H - 1) & (xx >= 0) &
                     (xx <= W - 1))
            v = img_dg[:, yi, xi]
            return jnp.where(valid[None], v, 0.0)

        Cg = C // deformable_groups

        def per_n(a_n, py, px, m_n):
            outs = []
            for g in range(deformable_groups):
                img = a_n[g * Cg:(g + 1) * Cg]
                yy, xx = py[g], px[g]
                y0g = jnp.floor(yy)
                x0g = jnp.floor(xx)
                wyg = yy - y0g
                wxg = xx - x0g
                val = (gather(img, y0g, x0g) * (1 - wyg) * (1 - wxg) +
                       gather(img, y0g + 1, x0g) * wyg * (1 - wxg) +
                       gather(img, y0g, x0g + 1) * (1 - wyg) * wxg +
                       gather(img, y0g + 1, x0g + 1) * wyg * wxg)
                if m_n is not None:
                    val = val * m_n[g][None]
                outs.append(val)
            return jnp.concatenate(outs, 0)  # [C, KH, KW, OH, OW]

        cols = jax.vmap(per_n)(a, pos_y, pos_x,
                               m if m is not None else
                               jnp.ones((N, deformable_groups, KH, KW, OH,
                                         OW), a.dtype))
        # cols: [N, C, KH, KW, OH, OW] → matmul with weight
        cols = cols.reshape(N, groups, C // groups * KH * KW, OH * OW)
        wg = w.reshape(groups, OC // groups, -1)
        out = jnp.einsum("ngkp,gok->ngop", cols, wg).reshape(N, OC, OH, OW)
        if bias is not None:
            out = out + rest[idx_r].reshape(1, OC, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply_op(fn, *args)


from ..nn.layer.layers import Layer as _Layer  # noqa: E402


class DeformConv2D(_Layer):
    """Deformable conv v1/v2 layer over the deform_conv2d functional.
    Parity: python/paddle/vision/ops.py DeformConv2D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        from ..nn import initializer as I
        # reference init: std = sqrt(2 / (in_channels * kh * kw)),
        # no groups division (vision/ops.py DeformConv2D)
        fan_in = in_channels * ks[0] * ks[1]
        default_init = I.Normal(0.0, float(np.sqrt(2.0 / fan_in)))
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr, default_initializer=default_init)
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, bias=self.bias, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups, groups=self._groups,
            mask=mask)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    rois = fpn_rois.numpy()
    ws = rois[:, 2] - rois[:, 0]
    hs = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(ws * hs)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, idxs = [], []
    for l in range(min_level, max_level + 1):
        sel = np.nonzero(lvl == l)[0]
        outs.append(Tensor(rois[sel]))
        idxs.append(sel)
    order = np.argsort(np.concatenate(idxs)) if idxs else np.array([])
    return outs, [Tensor(np.asarray([len(i)], np.int32)) for i in idxs], \
        Tensor(order.astype(np.int32))


def _np_greedy_nms(boxes, scores, thresh, eta, pixel_offset):
    """Greedy NMS with paddle's adaptive eta; returns kept indices in
    score order. Reference semantics (NMSFast in detection ops): each
    CANDIDATE is tested against the already-kept boxes using the
    threshold value current at candidate time — the eta decay applies
    after each keep, so later candidates face the decayed threshold."""
    off = 1.0 if pixel_offset else 0.0
    areas = (boxes[:, 2] - boxes[:, 0] + off) * \
            (boxes[:, 3] - boxes[:, 1] + off)
    order = np.argsort(-scores)
    keep = []
    adaptive = thresh
    for i in order:
        if keep:
            kept = np.asarray(keep)
            xx1 = np.maximum(boxes[i, 0], boxes[kept, 0])
            yy1 = np.maximum(boxes[i, 1], boxes[kept, 1])
            xx2 = np.minimum(boxes[i, 2], boxes[kept, 2])
            yy2 = np.minimum(boxes[i, 3], boxes[kept, 3])
            inter = np.maximum(0.0, xx2 - xx1 + off) * \
                np.maximum(0.0, yy2 - yy1 + off)
            iou = inter / (areas[i] + areas[kept] - inter + 1e-10)
            if np.any(iou > adaptive):
                continue
        keep.append(i)
        if eta < 1.0 and adaptive > 0.5:
            adaptive *= eta
    return np.asarray(keep, np.int64)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation. Host-side numpy (like the reference's CPU
    generate_proposals_v2 kernel,
    paddle/fluid/operators/detection/generate_proposals_v2_op.cc): decode
    anchor deltas, clip to image, filter small boxes, NMS, top-N.

    scores [N, A, H, W]; bbox_deltas [N, 4A, H, W]; img_size [N, 2]
    (h, w); anchors/variances [H, W, A, 4] (or flattened [H*W*A, 4]).
    Returns (rpn_rois [M, 4], rpn_roi_probs [M, 1][, rois_num])."""
    sc = scores.numpy()
    bd = bbox_deltas.numpy()
    im = img_size.numpy()
    an = anchors.numpy().reshape(-1, 4).astype(np.float64)
    va = variances.numpy().reshape(-1, 4).astype(np.float64)
    off = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, rois_num = [], [], []
    for n in range(sc.shape[0]):
        s = sc[n].transpose(1, 2, 0).reshape(-1)         # (H,W,A) order
        d = bd[n].transpose(1, 2, 0).reshape(-1, 4).astype(np.float64)
        if 0 < pre_nms_top_n < len(s):
            idx = np.argpartition(-s, pre_nms_top_n)[:pre_nms_top_n]
        else:
            idx = np.arange(len(s))
        idx = idx[np.argsort(-s[idx])]
        s_k, d_k, a_k, v_k = s[idx], d[idx], an[idx], va[idx]

        # decode (center-size with variances)
        aw = a_k[:, 2] - a_k[:, 0] + off
        ah = a_k[:, 3] - a_k[:, 1] + off
        acx = a_k[:, 0] + aw * 0.5
        acy = a_k[:, 1] + ah * 0.5
        cx = d_k[:, 0] * v_k[:, 0] * aw + acx
        cy = d_k[:, 1] * v_k[:, 1] * ah + acy
        clip = np.log(1000.0 / 16.0)  # reference kBBoxClipDefault
        w = np.exp(np.minimum(d_k[:, 2] * v_k[:, 2], clip)) * aw
        h = np.exp(np.minimum(d_k[:, 3] * v_k[:, 3], clip)) * ah
        props = np.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - off, cy + h * 0.5 - off], -1)

        imh, imw = float(im[n, 0]), float(im[n, 1])
        props[:, 0::2] = np.clip(props[:, 0::2], 0, imw - off)
        props[:, 1::2] = np.clip(props[:, 1::2], 0, imh - off)

        ms = max(float(min_size), 1.0)
        ws = props[:, 2] - props[:, 0] + off
        hs = props[:, 3] - props[:, 1] + off
        keep = (ws >= ms) & (hs >= ms)
        if pixel_offset:
            keep &= (props[:, 0] + ws / 2 < imw) & \
                    (props[:, 1] + hs / 2 < imh)
        keep = np.nonzero(keep)[0]
        if len(keep) == 0:
            props = np.zeros((1, 4), np.float32)
            s_k = np.zeros((1,), np.float32)
        else:
            props, s_k = props[keep], s_k[keep]
            if nms_thresh > 0:
                kept = _np_greedy_nms(props, s_k, nms_thresh, eta,
                                      pixel_offset)
                if 0 < post_nms_top_n < len(kept):
                    kept = kept[:post_nms_top_n]
                props, s_k = props[kept], s_k[kept]
        all_rois.append(props.astype(np.float32))
        all_probs.append(s_k.reshape(-1, 1).astype(np.float32))
        rois_num.append(len(props))

    rois = Tensor(np.concatenate(all_rois, 0))
    probs = Tensor(np.concatenate(all_probs, 0))
    if return_rois_num:
        return rois, probs, Tensor(np.asarray(rois_num, np.int32))
    return rois, probs


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (R-FCN). Parity:
    paddle/fluid/operators/psroi_pool_op.h — output channel c of bin
    (i, j) averages input channel (c*ph + i)*pw + j over the bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph_, pw_ = output_size

    def fn(feat, rois, rois_num):
        N, C, H, W = feat.shape
        oc = C // (ph_ * pw_)
        img_idx = _roi_image_index(rois.shape[0], rois_num)

        rs_w = jnp.round(rois[:, 0]) * spatial_scale
        rs_h = jnp.round(rois[:, 1]) * spatial_scale
        re_w = (jnp.round(rois[:, 2]) + 1.0) * spatial_scale
        re_h = (jnp.round(rois[:, 3]) + 1.0) * spatial_scale
        bh = jnp.maximum(re_h - rs_h, 0.1) / ph_
        bw = jnp.maximum(re_w - rs_w, 0.1) / pw_

        def one_roi(r):
            img = feat[img_idx[r]].reshape(oc, ph_, pw_, H, W)
            hstart = jnp.clip(jnp.floor(rs_h[r] + jnp.arange(ph_) * bh[r]),
                              0, H)
            hend = jnp.clip(
                jnp.ceil(rs_h[r] + (jnp.arange(ph_) + 1) * bh[r]), 0, H)
            wstart = jnp.clip(jnp.floor(rs_w[r] + jnp.arange(pw_) * bw[r]),
                              0, W)
            wend = jnp.clip(
                jnp.ceil(rs_w[r] + (jnp.arange(pw_) + 1) * bw[r]), 0, W)
            ymask = ((jnp.arange(H)[None, :] >= hstart[:, None]) &
                     (jnp.arange(H)[None, :] < hend[:, None]))
            xmask = ((jnp.arange(W)[None, :] >= wstart[:, None]) &
                     (jnp.arange(W)[None, :] < wend[:, None]))
            sums = jnp.einsum("cijhw,ih,jw->cij", img,
                              ymask.astype(feat.dtype),
                              xmask.astype(feat.dtype))
            area = ((hend - hstart)[:, None] *
                    (wend - wstart)[None, :]).astype(feat.dtype)
            return jnp.where(area > 0, sums / jnp.maximum(area, 1.0), 0.0)

        return jax.vmap(one_roi)(jnp.arange(rois.shape[0]))
    return apply_op(fn, x, boxes, boxes_num)


def read_file(path, name=None):
    with open(path, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(data)


def decode_jpeg(x, mode="unchanged", name=None):
    try:
        from PIL import Image
        import io
        img = Image.open(io.BytesIO(x.numpy().tobytes()))
        return Tensor(np.asarray(img).transpose(2, 0, 1))
    except ImportError as e:
        raise RuntimeError("decode_jpeg requires PIL in this image") from e


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss. Parity: python/paddle/vision/ops.py yolo_loss
    (fluid/operators/detection/yolov3_loss_op).

    Dense per-cell formulation (TPU-friendly: no dynamic shapes): each
    ground-truth box is binned to its responsible cell+anchor; objectness
    uses an IoU-vs-anchor ignore mask.
    """
    na = len(anchor_mask)
    an_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    an_sel = an_all[np.asarray(anchor_mask)]

    def fn(feat, gbox, glabel, *rest):
        gscore = rest[0] if rest else None
        N, C, H, W = feat.shape
        feat = feat.reshape(N, na, 5 + class_num, H, W)
        tx, ty = feat[:, :, 0], feat[:, :, 1]
        tw, th = feat[:, :, 2], feat[:, :, 3]
        tobj = feat[:, :, 4]
        tcls = feat[:, :, 5:]                       # [N,na,cls,H,W]
        in_size = float(downsample_ratio * H)

        B = gbox.shape[1]
        # gt in [0,1] cx,cy,w,h
        gx, gy = gbox[..., 0], gbox[..., 1]
        gw, gh = gbox[..., 2], gbox[..., 3]
        valid = (gw > 0) & (gh > 0)                 # [N,B]
        ci = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        ri = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)

        # best anchor (over ALL anchors) by IoU of (w,h); responsible only
        # if that anchor index is in anchor_mask
        aw = jnp.asarray(an_all[:, 0]) / in_size    # normalized
        ah = jnp.asarray(an_all[:, 1]) / in_size
        inter = jnp.minimum(gw[..., None], aw) * jnp.minimum(
            gh[..., None], ah)
        union = gw[..., None] * gh[..., None] + aw * ah - inter
        best = jnp.argmax(inter / (union + 1e-10), -1)    # [N,B]
        mask_idx = jnp.asarray(anchor_mask)
        sel = (best[..., None] == mask_idx)               # [N,B,na]
        resp = valid[..., None] & sel                     # [N,B,na]

        # targets in the responsible cell
        sgx = gx * W - ci.astype(gw.dtype)
        sgy = gy * H - ri.astype(gw.dtype)
        a_w = jnp.asarray(an_sel[:, 0])
        a_h = jnp.asarray(an_sel[:, 1])
        sgw = jnp.log(jnp.clip(gw * in_size, 1e-9)[..., None] /
                      a_w[None, None, :] + 1e-12)          # [N,B,na]
        sgh = jnp.log(jnp.clip(gh * in_size, 1e-9)[..., None] /
                      a_h[None, None, :] + 1e-12)
        box_scale = 2.0 - gw * gh                          # small-box boost

        sig = jax.nn.sigmoid
        bce = lambda p, t: jnp.maximum(p, 0) - p * t + jnp.log1p(
            jnp.exp(-jnp.abs(p)))

        ns = jnp.arange(N)[:, None, None]
        ai = jnp.arange(na)[None, None, :]
        px = tx[ns, ai, ri[..., None], ci[..., None]]      # [N,B,na]
        py = ty[ns, ai, ri[..., None], ci[..., None]]
        pw = tw[ns, ai, ri[..., None], ci[..., None]]
        ph = th[ns, ai, ri[..., None], ci[..., None]]
        w = resp.astype(feat.dtype) * box_scale[..., None]
        sc = gscore if gscore is not None else jnp.ones_like(gw)
        w = w * sc[..., None]
        # scale_x_y: decode is bx = sig(t)*s - (s-1)/2; invert it so the
        # sigmoid-space target matches the scaled decode (s=1 → identity)
        sgx_t = (sgx + (scale_x_y - 1) / 2) / scale_x_y
        sgy_t = (sgy + (scale_x_y - 1) / 2) / scale_x_y
        loss_xy = (bce(px, sgx_t[..., None]) + bce(py, sgy_t[..., None])) * w
        loss_wh = ((pw - sgw) ** 2 + (ph - sgh) ** 2) * 0.5 * \
            resp.astype(feat.dtype) * box_scale[..., None] * sc[..., None]

        # objectness: positive at responsible cells; negatives whose
        # predicted box overlaps any gt with IoU > ignore_thresh are
        # excluded from the negative loss (reference yolov3 semantics)
        obj_t = jnp.zeros((N, na, H, W), feat.dtype)
        obj_t = obj_t.at[ns, ai, ri[..., None], ci[..., None]].max(
            resp.astype(feat.dtype))
        gxc = jnp.arange(W).reshape(1, 1, 1, W)
        gyc = jnp.arange(H).reshape(1, 1, H, 1)
        p_cx = (sig(tx) * scale_x_y - (scale_x_y - 1) / 2 + gxc) / W
        p_cy = (sig(ty) * scale_x_y - (scale_x_y - 1) / 2 + gyc) / H
        p_w = jnp.exp(jnp.clip(tw, -10, 10)) * \
            jnp.asarray(an_sel[:, 0]).reshape(1, na, 1, 1) / in_size
        p_h = jnp.exp(jnp.clip(th, -10, 10)) * \
            jnp.asarray(an_sel[:, 1]).reshape(1, na, 1, 1) / in_size

        def iou_vs_gt(b):  # gt index b → IoU [N,na,H,W]
            bx1, bx2 = gx[:, b] - gw[:, b] / 2, gx[:, b] + gw[:, b] / 2
            by1, by2 = gy[:, b] - gh[:, b] / 2, gy[:, b] + gh[:, b] / 2
            r = (1, 1, 1)
            px1, px2 = p_cx - p_w / 2, p_cx + p_w / 2
            py1, py2 = p_cy - p_h / 2, p_cy + p_h / 2
            iw = jnp.clip(jnp.minimum(px2, bx2.reshape(-1, *r)) -
                          jnp.maximum(px1, bx1.reshape(-1, *r)), 0)
            ih = jnp.clip(jnp.minimum(py2, by2.reshape(-1, *r)) -
                          jnp.maximum(py1, by1.reshape(-1, *r)), 0)
            inter_a = iw * ih
            union_a = p_w * p_h + (gw[:, b] * gh[:, b]).reshape(-1, *r) \
                - inter_a
            return jnp.where(valid[:, b].reshape(-1, *r),
                             inter_a / (union_a + 1e-10), 0.0)
        best_iou = jnp.max(jnp.stack([iou_vs_gt(b) for b in range(B)]), 0)
        loss_obj_pos = bce(tobj, obj_t) * obj_t
        neg_mask = (1.0 - obj_t) * (best_iou <= ignore_thresh).astype(
            feat.dtype)
        loss_obj_neg = bce(tobj, jnp.zeros_like(tobj)) * neg_mask
        loss_obj = loss_obj_pos + loss_obj_neg

        # classification at responsible cells; label smoothing puts 1-1/C
        # on the true class and 1/C on the rest
        onehot = jax.nn.one_hot(glabel, class_num, dtype=feat.dtype)
        if use_label_smooth:
            smooth = 1.0 / max(class_num, 1)
            onehot = onehot * (1 - smooth) + (1 - onehot) * smooth
        pcls = tcls[ns[..., None], ai[..., None],
                    jnp.arange(class_num)[None, None, None, :],
                    ri[..., None, None], ci[..., None, None]]  # [N,B,na,cls]
        loss_cls = bce(pcls, onehot[:, :, None, :]) * \
            resp[..., None].astype(feat.dtype)

        per_img = (loss_xy.sum((1, 2)) + loss_wh.sum((1, 2)) +
                   loss_obj.sum((1, 2, 3)) + loss_cls.sum((1, 2, 3)))
        return per_img
    args = (x, gt_box, gt_label) + ((gt_score,) if gt_score is not None
                                    else ())
    return apply_op(fn, *args)


class RoIPool:
    """Layer wrapper over roi_pool. Parity: vision/ops.py RoIPool."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class RoIAlign:
    """Layer wrapper over roi_align. Parity: vision/ops.py RoIAlign."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class PSRoIPool:
    """Layer wrapper over psroi_pool. Parity: vision/ops.py PSRoIPool."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


__all__ += ["yolo_loss", "RoIPool", "RoIAlign", "PSRoIPool"]
