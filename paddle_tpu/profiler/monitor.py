"""Global metrics registry: counters / gauges / histograms + a JSONL
per-step exporter.

Every framework hot path reports here (jit compiles and retraces, train
steps, DataLoader batch waits, collectives, device memory peaks), so a
training process carries its own always-on flight recorder.

Async-pipeline signals (the host-overlap story, docs/PERFORMANCE.md
"Hiding the host"): `host.blocked_s` (histogram — every time the host
actually blocked on a device read, recorded by DeferredLoss; sum via
`host_blocked_s()`), `prefetch.h2d_bytes` (counter — bytes staged onto
the device by the prefetch ring), `prefetch.depth` (gauge — ring fill
level; pinned at 0 means the step loop is data-bound).

Distributed signals (the distributed observatory,
profiler/dist_observatory.py — docs/OBSERVABILITY.md "The distributed
observatory"): `collective.<kind>.calls` / `collective.<kind>.bytes`
counters (every collective call site), `train.step_time_device_s` /
`train.mfu_measured` / `train.overlap_fraction` gauges (the sampled
device-time probe: measured step time, cost-analysis-FLOPs-over-
MEASURED-time MFU, and the non-collective-wait share of the window),
`dist.rankstats` counter (per-rank `kind:"rankstat"` records emitted)
and `dist.stragglers` counter (rank-0 `event:"straggler"` detections).
The sampled per-collective detail (`kind:"collective"`: op, group,
bytes, wall_s, bus-bandwidth GB/s) and the periodic `kind:"rankstat"`
records ride the JSONL exporter below.

Serving signals (the continuous-batching engines, docs/SERVING.md):
`serve.queue_depth` / `serve.shared_pages` / `serve.kv_free_pages` /
`serve.kv_held_pages` / `serve.kv_registered_pages` /
`serve.kv_evictable_pages` / `serve.kv_peak_held_pages` gauges,
`serve.batch_size` / `serve.latency_s` / `serve.ttft_s` /
`serve.tpot_s` histograms, `serve.requests` / `serve.rejected` /
`serve.expired` / `serve.pad_tokens` / `serve.retraces` /
`serve.errors` / `serve.prefix_hits` / `serve.chunked_prefill_tokens` /
`serve.generated_tokens` / `serve.goodput_tokens` /
`serve.wasted_tokens` counters (the kv_*/goodput split is maintained by
profiler/serve_observatory.py, which also emits the per-request
`kind:"request"` and page-pool `kind:"kvcache"` records).
Histograms keep a bounded reservoir of recent observations, so tail
latency is queryable in-process: `histogram("serve.latency_s")
.percentile(99)` — and `snapshot()` carries `p50`/`p99` from the same
reservoir, so `metrics_snapshot()` and `load_report()` serialize tail
latency without callers reaching into `percentile()`.

Registry usage:

    from paddle_tpu.profiler import monitor
    monitor.counter("jit.retraces").inc()
    monitor.gauge("train.mfu").set(0.41)
    monitor.histogram("dataloader.wait_s").observe(dt)
    monitor.metrics_snapshot()   # {name: value-or-stats}

Exporter: with `PADDLE_TPU_METRICS_FILE` set, `export_step(record)`
appends ONE JSON object per line, tagged with a wall-clock `ts`, the
process `rank` (from the launch env), and a `kind`. TrainStep /
HybridTrainStep call it once per optimizer step with the documented step
schema (step, step_time_s, compile_s, cache_hit, peak_bytes, flops, mfu
— validated by tools/check_metrics_schema.py); see docs/OBSERVABILITY.md.

Record kinds riding the exporter (one line each; full field schemas in
tools/check_metrics_schema.py):

    step        one per optimizer step (TrainStep / HybridTrainStep)
    scan        one per scanned-layer-group step (scan-over-layers path)
    serve       one per dispatched serving batch (GenerationEngine)
    health      one per resolved async health vector (health monitor)
    event       structured anomaly/lifecycle events (flight recorder)
    compile     one per AOT-compiled executable signature (aot_warmup)
    warm        one per resolved warm set (aot_warmup manifests)
    lint        one per static-analysis finding (tools/lint/paddlelint)
    seed        one per compile-cache seeding (persistent cache)
    ckpt        one per checkpoint save/restore/GC (checkpointing)
    request     ONE per request at its terminal state (serve observatory;
                outcome "handoff" closes the prefill half of a
                disaggregated request, the decode half re-emits)
    route       ONE per router decision: dispatch / reject / handoff
    kvcache     periodic KV page-pool snapshot (serve observatory)
    collective  sampled per-collective timing (dist observatory)
    rankstat    periodic per-rank skew telemetry (dist observatory)
    journey     ONE per handed-off request at decode-terminal time:
                queue/prefill/handoff-gap/decode phase split
                (profiler/fleet_observatory.py)
    fleet       periodic router-level fleet snapshot: per-engine
                rollup, shared-pool claims, rates, SLO attainment
                (fleet observatory)
    harness     ONE summary per tools/load_harness.py open-loop run
    memory      periodic device-memory attribution: per-tag ledger
                bytes, attributed/unattributed split, pool occupancy
                + fragmentation (mem observatory; train and serve
                cadences both emit it)
"""
import collections
import json
import os
import threading
import time

from . import flight_recorder

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge",
           "histogram", "get_metric", "metrics_snapshot", "reset_metrics",
           "rank", "metrics_file", "export_step", "host_blocked_s",
           "set_clock_offset", "clock_offset"]

# this rank's estimated wall-clock offset vs rank 0 (seconds), set by
# the distributed observatory's coordinator handshake
# (dist_observatory.clock_sync at init_parallel_env); stamped onto
# every exported record when nonzero so tools/merge_traces.py can
# clock-align per-rank artifacts
_clock_offset = [0.0]


def set_clock_offset(offset_s):
    _clock_offset[0] = float(offset_s)


def clock_offset():
    return _clock_offset[0]

_lock = threading.RLock()
_export_lock = threading.Lock()  # file appends only: registry ops must
_registry = {}                   # never stall behind metrics-file I/O


class Counter:
    """Monotonically increasing count (calls, bytes, cache hits)."""
    kind = "counter"

    def __init__(self, name):
        self.name = name
        self.value = 0

    def inc(self, v=1):
        with _lock:
            self.value += v
            out = self.value
        flight_recorder.record_sample(self.name, "counter", out)
        return out

    def snapshot(self):
        return self.value


class Gauge:
    """Last-observed value (peak bytes, current MFU)."""
    kind = "gauge"

    def __init__(self, name):
        self.name = name
        self.value = 0

    def set(self, v):
        with _lock:
            self.value = v
        flight_recorder.record_sample(self.name, "gauge", v)
        return v

    def snapshot(self):
        return self.value


class Histogram:
    """Streaming count/sum/min/max/last of observations (durations),
    plus a bounded reservoir of the most recent `RESERVOIR` samples for
    percentile queries (serving tail latency: p50/p99)."""
    kind = "histogram"

    RESERVOIR = 2048  # recent-window size for percentile()

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.last = 0.0
        self._samples = collections.deque(maxlen=self.RESERVOIR)

    def observe(self, v):
        v = float(v)
        with _lock:
            self.count += 1
            self.sum += v
            self.last = v
            self._samples.append(v)
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
        flight_recorder.record_sample(self.name, "histogram", v)

    @property
    def avg(self):
        return self.sum / self.count if self.count else 0.0

    @staticmethod
    def _nearest_rank(s, p):
        """Nearest-rank pick from an already-sorted sample list."""
        if not s:
            return 0.0
        idx = min(len(s) - 1,
                  max(0, int(round(float(p) / 100.0 * (len(s) - 1)))))
        return s[idx]

    def percentile(self, p):
        """Nearest-rank percentile (p in [0, 100]) over the reservoir of
        the last RESERVOIR observations — a recent window, not all-time
        (all-time min/max/avg stay exact in the streaming fields)."""
        with _lock:
            s = sorted(self._samples)
        return self._nearest_rank(s, p)

    def snapshot(self):
        # p50/p99 ride along (reservoir window, like percentile()): the
        # serialized forms — metrics_snapshot, host_stats.json, serving
        # load_report — carry tail latency without a percentile() call.
        # ONE sort serves both ranks (metrics_snapshot walks every
        # histogram under the registry lock)
        with _lock:
            s = sorted(self._samples)
            snap = {"count": self.count, "sum": self.sum,
                    "avg": self.avg,
                    "min": self.min if self.count else 0.0,
                    "max": self.max, "last": self.last}
        snap["p50"] = self._nearest_rank(s, 50)
        snap["p99"] = self._nearest_rank(s, 99)
        return snap


def _get_or_create(name, cls):
    with _lock:
        m = _registry.get(name)
        if m is None:
            m = _registry[name] = cls(name)
        elif not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{type(m).__name__}, requested {cls.__name__}")
        return m


def counter(name):
    return _get_or_create(name, Counter)


def gauge(name):
    return _get_or_create(name, Gauge)


def histogram(name):
    return _get_or_create(name, Histogram)


def get_metric(name):
    return _registry.get(name)


def metrics_snapshot():
    """{name: scalar (counter/gauge) or stats dict (histogram)} — JSON
    serializable, sorted by name."""
    with _lock:
        return {name: _registry[name].snapshot()
                for name in sorted(_registry)}


def reset_metrics():
    with _lock:
        _registry.clear()


def host_blocked_s():
    """Total seconds the host has spent blocked on device reads (the
    `host.blocked_s` histogram sum) — ~0 in a healthy async step loop,
    where the only blocks are log_freq/epoch boundaries. bench.py
    reports the steady-phase delta of this in its phase breakdown."""
    m = get_metric("host.blocked_s")
    return float(m.sum) if m is not None else 0.0


def rank():
    """This process's rank from the launch env (0 single-controller).
    Read from env, NOT jax.process_index(): telemetry must never force
    backend init."""
    for var in ("PADDLE_TPU_PROCESS_ID", "PADDLE_TRAINER_ID"):
        v = os.environ.get(var)
        if v is not None and v != "":
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def metrics_file():
    """The JSONL export path, or None when export is off."""
    return os.environ.get("PADDLE_TPU_METRICS_FILE") or None


def export_step(record, kind="step", _ring=True):
    """Append one rank-tagged JSON line to PADDLE_TPU_METRICS_FILE.
    The record also lands in the flight-recorder ring (always on, file
    or no file), so a debug bundle carries the recent step/serve/health
    tail even for a process that never configured an export path.
    Returns False when the env var is unset or the write failed; never
    raises — telemetry must not take down a train loop."""
    rec = {"ts": time.time(), "rank": rank(), "kind": kind}
    if _clock_offset[0]:
        rec["clock_offset_s"] = _clock_offset[0]
    rec.update(record)
    if _ring:  # events ring-record themselves (flight_recorder)
        flight_recorder.record_record(rec)
    path = metrics_file()
    if not path:
        return False
    try:
        line = json.dumps(rec)
    except (TypeError, ValueError):
        return False
    try:
        with _export_lock, open(path, "a") as f:
            f.write(line + "\n")
    except OSError:
        return False
    return True
