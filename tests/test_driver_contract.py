"""The driver's proof-points must keep working: bench.py prints ONE JSON
line with the contract keys, and __graft_entry__ exposes entry() +
dryrun_multichip()."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"])
    return env


@pytest.mark.heavy
def test_bench_emits_contract_json():
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          env=_env(), cwd=REPO, capture_output=True,
                          text=True, timeout=280)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, lines
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in rec, rec
    assert rec["unit"] == "tokens/s/chip" and rec["value"] > 0


@pytest.mark.heavy
def test_bench_rejects_bad_remat():
    env = _env()
    env["BENCH_REMAT"] = "bogus"
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=280)
    # CPU path ignores BENCH_REMAT (config not applied off-TPU), so it
    # still succeeds — but it must never print a half-line or crash ugly
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1 and json.loads(lines[0])


def test_graft_entry_compiles():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; import jax; f, a = g.entry(); "
         "out = jax.jit(f)(*a); print('SHAPE', out.shape)"],
        env=_env(), cwd=REPO, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "SHAPE" in proc.stdout


@pytest.mark.heavy
def test_dryrun_multichip_8():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('OK')"],
        env=_env(), cwd=REPO, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout
