"""Fifth sweep: static append_backward/scope_guard, vision transforms
(ColorJitter, RandomRotation, Grayscale, erase) vs torchvision-style
oracles / invariants."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
import paddle_tpu.vision.transforms as T


class TestStaticTail:
    def test_append_backward_returns_param_grads(self):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        main = static.Program()
        start = static.Program()
        with static.program_guard(main, start):
            x = static.data("x", [None, 4], "float32")
            y = static.data("y", [None, 1], "float32")
            h = static.nn.fc(x, 1)
            loss = ((h - y) ** 2).mean() if hasattr(h, "mean") else h
            pgs = static.append_backward(loss)
        assert pgs, "no parameter gradients returned"

    def test_scope_guard_isolated(self):
        with static.scope_guard(static.Scope()):
            pass  # context manager contract only


class TestTransforms:
    def _img(self):
        rng = np.random.RandomState(0)
        return (rng.rand(16, 16, 3) * 255).astype(np.uint8)

    def test_grayscale_luma_weights(self):
        img = self._img()
        out = T.Grayscale()(img)
        arr = np.asarray(out)
        want = (0.299 * img[..., 0] + 0.587 * img[..., 1]
                + 0.114 * img[..., 2])
        got = arr[..., 0] if arr.ndim == 3 else arr
        np.testing.assert_allclose(got.astype(np.float32), want, atol=1.0)

    def test_color_jitter_deterministic_range(self):
        paddle.seed(0)
        img = self._img()
        out = np.asarray(T.ColorJitter(brightness=0.2, contrast=0.2,
                                       saturation=0.2, hue=0.1)(img))
        assert out.shape == img.shape
        assert out.dtype == img.dtype

    def test_random_rotation_90_exact(self):
        img = self._img()
        out = np.asarray(T.RandomRotation(degrees=(90, 90))(img))
        assert out.shape == img.shape
        # rot by exactly 90deg ≈ np.rot90 up to interpolation at borders
        want = np.rot90(img, k=1, axes=(0, 1))
        center = (slice(4, 12), slice(4, 12))
        diff = np.abs(out[center].astype(np.int32)
                      - want[center].astype(np.int32))
        assert np.median(diff) <= 2.0

    def test_erase_masks_region(self):
        img = paddle.to_tensor(
            np.ones((3, 8, 8), np.float32))
        out = T.erase(img, 2, 2, 3, 3,
                      v=paddle.to_tensor(np.zeros((3, 3, 3), np.float32)))
        arr = out.numpy()
        assert (arr[:, 2:5, 2:5] == 0).all()
        assert arr.sum() == 3 * 64 - 3 * 9

    def test_compose_normalize_totensor(self):
        img = self._img()
        pipe = T.Compose([T.ToTensor(),
                          T.Normalize(mean=[0.5, 0.5, 0.5],
                                      std=[0.5, 0.5, 0.5])])
        out = pipe(img)
        arr = out.numpy() if hasattr(out, "numpy") else np.asarray(out)
        assert arr.shape == (3, 16, 16)
        assert arr.min() >= -1.001 and arr.max() <= 1.001
