"""Shape/layout manipulation ops. Parity: python/paddle/tensor/manipulation.py."""
import builtins
import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op


def _axes(a):
    if a is None:
        return None
    if isinstance(a, Tensor):
        a = a.tolist()
    if isinstance(a, (list, tuple)):
        return tuple(int(v) for v in a)
    return int(a)


def _static_shape(shape):
    if isinstance(shape, Tensor):
        arr = shape.numpy().reshape(-1)  # 0-d shape tensor = one dim
        return tuple(int(v) for v in arr)
    return tuple(int(s.item() if isinstance(s, Tensor) else s) for s in shape)


def reshape(x, shape, name=None):
    shape = _static_shape(shape)
    # reference semantics (tensor/manipulation.py reshape): a 0 in
    # `shape` copies the dimension from the input at the same position
    if 0 in shape:
        shape = tuple(x.shape[i] if s == 0 else s
                      for i, s in enumerate(shape))
    return apply_op(lambda a: jnp.reshape(a, shape), x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._bind(out._slot)
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(a):
        nd = a.ndim
        s0 = start_axis % nd if nd else 0
        s1 = stop_axis % nd if nd else 0
        new = a.shape[:s0] + (-1,) + a.shape[s1 + 1:]
        return a.reshape(new)
    return apply_op(fn, x)


def squeeze(x, axis=None, name=None):
    ax = _axes(axis)
    def fn(a):
        if ax is None:
            return jnp.squeeze(a)
        axes = ax if isinstance(ax, tuple) else (ax,)
        axes = tuple(i % a.ndim for i in axes if a.shape[i % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply_op(fn, x)


def unsqueeze(x, axis, name=None):
    ax = _axes(axis)
    def fn(a):
        axes = ax if isinstance(ax, tuple) else (ax,)
        out = a
        for i in sorted(axes):
            out = jnp.expand_dims(out, i)
        return out
    return apply_op(fn, x)


unsqueeze_ = unsqueeze
squeeze_ = squeeze


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op(lambda *xs: jnp.concatenate(xs, axis=axis), *x)


def stack(x, axis=0, name=None):
    return apply_op(lambda *xs: jnp.stack(xs, axis=axis), *x)


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    def fn(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=axis))
        secs = [int(s) for s in num_or_sections]
        total = a.shape[axis]
        if any(s == -1 for s in secs):
            known = sum(s for s in secs if s != -1)
            secs = [total - known if s == -1 else s for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        return tuple(jnp.split(a, idx, axis=axis))
    return list(apply_op(fn, x))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def unbind(x, axis=0, name=None):
    def fn(a):
        return tuple(jnp.squeeze(p, axis=axis)
                     for p in jnp.split(a, a.shape[axis], axis=axis))
    return list(apply_op(fn, x))


unstack = unbind


def tile(x, repeat_times, name=None):
    reps = _static_shape(repeat_times)
    return apply_op(lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    shape = _static_shape(shape)
    def fn(a):
        tgt = tuple(a.shape[i - (len(shape) - a.ndim)] if s == -1 else s
                    for i, s in enumerate(shape))
        return jnp.broadcast_to(a, tgt)
    return apply_op(fn, x)


broadcast_to = expand


def expand_as(x, y, name=None):
    return apply_op(lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


def broadcast_tensors(input=None, name=None, inputs=None):
    # reference signature names the list `input`; accept the older
    # positional `inputs` spelling too
    tensors = input if input is not None else inputs
    outs = apply_op(lambda *xs: tuple(jnp.broadcast_arrays(*xs)), *tensors)
    return list(outs)


def transpose(x, perm, name=None):
    perm = _axes(perm)
    return apply_op(lambda a: jnp.transpose(a, perm), x)


def t(x, name=None):
    def fn(a):
        if a.ndim < 2:
            return a
        return a.T
    return apply_op(fn, x)


def moveaxis(x, source, destination, name=None):
    return apply_op(
        lambda a: jnp.moveaxis(a, _axes(source), _axes(destination)), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, axis0, axis1), x)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    x._bind(out._slot)
    return x


def reverse(x, axis, name=None):
    """Alias of flip (reference fluid.layers.reverse)."""
    return flip(x, axis)


def flip(x, axis, name=None):
    ax = _axes(axis)
    return apply_op(lambda a: jnp.flip(a, axis=ax), x)


def roll(x, shifts, axis=None, name=None):
    sh = _axes(shifts)
    ax = _axes(axis)
    return apply_op(lambda a: jnp.roll(a, sh, axis=ax), x)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op(lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim > 1
                                          else i, axis=axis), x, index)


def gather_nd(x, index, name=None):
    def fn(a, idx):
        k = idx.shape[-1]
        flat_idx = tuple(idx[..., i] for i in range(k))
        return a[flat_idx]
    return apply_op(fn, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        base = a.at[i].set(jnp.zeros_like(u))
        return base.at[i].add(u)
    return apply_op(fn, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._bind(out._slot)
    return x


def scatter_nd_add(x, index, updates, name=None):
    def fn(a, idx, u):
        k = idx.shape[-1]
        return a.at[tuple(idx[..., i] for i in range(k))].add(u)
    return apply_op(fn, x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    z = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(z, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op(lambda a, i: jnp.take(a, i, axis=axis), x, index)


def index_sample(x, index):
    def fn(a, i):
        rows = jnp.arange(a.shape[0])[:, None]
        return a[rows, i]
    return apply_op(fn, x, index)


def index_add(x, index, axis, value, name=None):
    def fn(a, i, v):
        idx = [slice(None)] * a.ndim
        moved = jnp.moveaxis(a, axis, 0)
        out = moved.at[i].add(jnp.moveaxis(v, axis, 0))
        return jnp.moveaxis(out, 0, axis)
    return apply_op(fn, x, index, value)


def masked_select(x, mask, name=None):
    # dynamic output shape: eager-only (not jittable), same as reference
    out = x.numpy()[np.asarray(mask.numpy(), dtype=bool)]
    return Tensor(out)


def masked_fill(x, mask, value, name=None):
    v = value.value if isinstance(value, Tensor) else value
    return apply_op(lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a),
                    x, mask)


def take_along_axis(arr, indices, axis, name=None):
    return apply_op(lambda a, i: jnp.take_along_axis(a, i, axis=axis),
                    arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def fn(a, i, v):
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        if reduce == "add":
            return _put_along(a, i, v, axis, "add")
        if reduce == "multiply" or reduce == "mul":
            return _put_along(a, i, v, axis, "multiply")
        return _put_along(a, i, v, axis, "assign")
    if not isinstance(values, Tensor):
        values = Tensor(np.asarray(values))
    return apply_op(fn, arr, indices, values)


def _put_along(a, idx, vals, axis, mode):
    moved = jnp.moveaxis(a, axis, -1)
    mi = jnp.moveaxis(idx, axis, -1)
    mv = jnp.moveaxis(vals, axis, -1)
    grid = jnp.indices(mi.shape)
    index_tuple = tuple(grid[d] for d in range(mi.ndim - 1)) + (mi,)
    if mode == "add":
        out = moved.at[index_tuple].add(mv)
    elif mode == "multiply":
        out = moved.at[index_tuple].multiply(mv)
    else:
        out = moved.at[index_tuple].set(mv)
    return jnp.moveaxis(out, -1, axis)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    res = np.unique(x.numpy(), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    a = x.numpy()
    if axis is None:
        a = a.reshape(-1)
        keep = np.concatenate([[True], a[1:] != a[:-1]])
    else:
        diff = np.any(np.diff(a, axis=axis) != 0,
                      axis=tuple(i for i in range(a.ndim) if i != axis))
        keep = np.concatenate([[True], diff])
        a = np.compress(keep, x.numpy(), axis=axis)
        return Tensor(a)
    out = a[keep]
    rets = [Tensor(out)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        rets.append(Tensor(inv))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, len(a)))
        rets.append(Tensor(counts))
    return rets[0] if len(rets) == 1 else tuple(rets)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(i):
        size = (index_num + nshards - 1) // nshards
        lo, hi = shard_id * size, (shard_id + 1) * size
        ok = (i >= lo) & (i < hi)
        return jnp.where(ok, i - lo, ignore_value)
    return apply_op(fn, input)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = repeats.numpy()
        a = x.numpy()
        return Tensor(np.repeat(a, reps, axis=axis))
    return apply_op(lambda a: jnp.repeat(a, repeats, axis=axis), x)


def as_complex(x, name=None):
    return apply_op(lambda a: a[..., 0] + 1j * a[..., 1], x)


def as_real(x, name=None):
    return apply_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                    x)


def tensordot(x, y, axes=2, name=None):
    """Reference semantics (tensor/manipulation.py tensordot): an int
    contracts the last n axes of x with the first n of y; a flat list
    contracts the SAME axes on both operands; a pair of lists applies
    the first to x and the second to y, with the shorter list extended
    by the tail of the longer one (axes expansion), and an empty second
    list meaning "same as the first"."""
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    if isinstance(ax, int):
        return apply_op(lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)
    ax = [a.tolist() if isinstance(a, Tensor) else a for a in ax]
    if ax and not isinstance(ax[0], (list, tuple)):
        xa = ya = [int(v) for v in ax]  # flat list: same axes both sides
    else:
        xa = [int(v) for v in (ax[0] if len(ax) >= 1 else [])]
        ya = [int(v) for v in (ax[1] if len(ax) >= 2 else [])]
        if len(xa) < len(ya):
            xa = xa + ya[len(xa):]
        elif len(ya) < len(xa):
            ya = ya + xa[len(ya):]
    return apply_op(
        lambda a, b: jnp.tensordot(a, b, axes=(tuple(xa), tuple(ya))), x, y)


def slice(input, axes, starts, ends):
    def val(v):
        return int(v.item()) if isinstance(v, Tensor) else int(v)
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(val(s), val(e))
        return a[tuple(idx)]
    return apply_op(fn, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(int(s), int(e), int(st))
        return a[tuple(idx)]
    return apply_op(fn, x)


def crop(x, shape=None, offsets=None, name=None):
    shape = _static_shape(shape)
    offs = [0] * len(shape) if offsets is None else [
        int(o.item() if isinstance(o, Tensor) else o) for o in offsets]
    def fn(a):
        idx = tuple(builtins.slice(o, o + (s if s != -1 else a.shape[i] - o))
                    for i, (o, s) in enumerate(zip(offs, shape)))
        return a[idx]
    return apply_op(fn, x)


def cast(x, dtype):
    return x.astype(dtype)


def fill_(x, value):
    x._bind(apply_op(lambda a: jnp.full_like(a, value), x)._slot)
    return x


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    def fn(a):
        n = min(a.shape[-2:])
        i = jnp.arange(n - abs(offset))
        r = i + (-offset if offset < 0 else 0)
        c = i + (offset if offset > 0 else 0)
        return a.at[..., r, c].set(value)
    x._bind(apply_op(fn, x)._slot)
    return x


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Fill the (dim1, dim2) diagonal of x with tensor y. y's shape is
    x's shape with dim1/dim2 removed and the diagonal length appended
    (for 2-d x, just [diag_len]). Parity: reference
    tensor/manipulation.py fill_diagonal_tensor."""
    def fn(a, b):
        nd = a.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        # move the diagonal plane to the last two axes
        rest = [i for i in range(nd) if i not in (d1, d2)]
        perm = rest + [d1, d2]
        ap = jnp.transpose(a, perm)
        h, w = ap.shape[-2], ap.shape[-1]
        n = min(h + min(offset, 0), w - max(offset, 0))
        i = jnp.arange(n)
        r = i + (-offset if offset < 0 else 0)
        c = i + (offset if offset > 0 else 0)
        out = ap.at[..., r, c].set(b.astype(a.dtype))
        inv = [0] * nd
        for pos, axis in enumerate(perm):
            inv[axis] = pos
        return jnp.transpose(out, inv)
    return apply_op(fn, x, y)


def fill_diagonal_tensor_(x, y, offset=0, dim1=0, dim2=1, name=None):
    x._bind(fill_diagonal_tensor(x, y, offset, dim1, dim2)._slot)
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def put_along_axis_(arr, indices, values, axis, reduce="assign",
                    name=None):
    out = put_along_axis(arr, indices, values, axis, reduce)
    arr._bind(out._slot)
    return arr
