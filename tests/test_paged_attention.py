"""Paged KV-cache attention (continuous batching): numerics vs dense
attention, page reuse after free, ragged batches, out-of-pages error."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.paged_attention import PagedKVCache, paged_attention

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no


def _dense_ref(q, hist_k, hist_v):
    D = q.shape[-1]
    s = np.einsum("hd,thd->ht", q, hist_k) / np.sqrt(D)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("ht,thd->hd", p, hist_v)


class TestPagedAttention:
    def test_matches_dense_ragged_batch(self):
        rng = np.random.RandomState(0)
        H, D, P = 2, 4, 4
        cache = PagedKVCache(n_layers=1, n_pages=16, page_size=P,
                             n_heads=H, head_dim=D)
        hists = {}
        for sid, T in (("a", 3), ("b", 9), ("c", 6)):  # ragged lengths
            cache.add_sequence(sid)
            k = rng.randn(T, H, D).astype(np.float32)
            v = rng.randn(T, H, D).astype(np.float32)
            cache.extend(sid, 0, jnp.asarray(k), jnp.asarray(v))
            cache.advance(sid, T)
            hists[sid] = (k, v)
        q = rng.randn(3, H, D).astype(np.float32)
        out = cache.attend(0, jnp.asarray(q), ["a", "b", "c"])
        for i, sid in enumerate(["a", "b", "c"]):
            want = _dense_ref(q[i], *hists[sid])
            np.testing.assert_allclose(np.asarray(out)[i], want,
                                       rtol=1e-4, atol=1e-5)

    def test_incremental_decode_matches_one_shot(self):
        rng = np.random.RandomState(1)
        H, D, P = 2, 4, 4
        cache = PagedKVCache(1, 8, P, H, D)
        cache.add_sequence("s")
        ks = rng.randn(7, H, D).astype(np.float32)
        vs = rng.randn(7, H, D).astype(np.float32)
        for t in range(7):  # token-by-token appends crossing page edges
            cache.extend("s", 0, jnp.asarray(ks[t:t + 1]),
                         jnp.asarray(vs[t:t + 1]))
            cache.advance("s", 1)
        q = rng.randn(1, H, D).astype(np.float32)
        out = cache.attend(0, jnp.asarray(q), ["s"])
        np.testing.assert_allclose(np.asarray(out)[0],
                                   _dense_ref(q[0], ks, vs),
                                   rtol=1e-4, atol=1e-5)

    def test_pages_reused_after_free(self):
        H, D, P = 1, 2, 2
        cache = PagedKVCache(1, 4, P, H, D)  # 3 usable pages (page 0 pad)
        cache.add_sequence("x")
        cache.extend("x", 0, jnp.zeros((6, H, D)), jnp.zeros((6, H, D)))
        cache.advance("x", 6)
        assert cache.n_free_pages() == 0
        cache.free_sequence("x")
        assert cache.n_free_pages() == 3
        cache.add_sequence("y")  # reuse must work
        cache.extend("y", 0, jnp.ones((4, H, D)), jnp.ones((4, H, D)))
        cache.advance("y", 4)
        assert cache.length("y") == 4

    def test_out_of_pages_raises(self):
        cache = PagedKVCache(1, 3, 2, 1, 2)  # 2 usable pages = 4 tokens
        cache.add_sequence("x")
        with pytest.raises(RuntimeError, match="out of pages"):
            cache.extend("x", 0, jnp.zeros((6, 1, 2)),
                         jnp.zeros((6, 1, 2)))

    def test_jit_stable_across_steps(self):
        """The gather+softmax compiles once per (B, max_pages) bucket —
        repeated decode steps reuse the program."""
        rng = np.random.RandomState(2)
        H, D, P = 2, 4, 4
        cache = PagedKVCache(1, 16, P, H, D)
        cache.add_sequence("s")
        cache.extend("s", 0, jnp.asarray(rng.randn(8, H, D), jnp.float32),
                     jnp.asarray(rng.randn(8, H, D), jnp.float32))
        cache.advance("s", 8)
        jit_pa = jax.jit(paged_attention)
        pt, lens = cache.batch_views(["s"])
        q = jnp.asarray(rng.randn(1, H, D), jnp.float32)
        a = jit_pa(q, cache.k[0], cache.v[0], pt, lens)
        b = jit_pa(q, cache.k[0], cache.v[0], pt, lens)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        assert jit_pa._cache_size() == 1


class TestReviewHardening:
    def test_failed_allocation_leaves_pool_intact(self):
        """Out-of-pages must not leak pages to the failed sequence —
        another (smaller) request must still fit."""
        cache = PagedKVCache(1, 4, 2, 1, 2)  # 3 usable pages, 6 tokens
        cache.add_sequence("big")
        with pytest.raises(RuntimeError):
            cache.extend("big", 0, jnp.zeros((8, 1, 2)),
                         jnp.zeros((8, 1, 2)))
        assert cache.n_free_pages() == 3  # nothing leaked
        cache.add_sequence("small")
        cache.extend("small", 0, jnp.zeros((4, 1, 2)),
                     jnp.zeros((4, 1, 2)))
        cache.advance("small", 4)

    def test_width_buckets_power_of_two(self):
        cache = PagedKVCache(1, 32, 2, 1, 2)
        cache.add_sequence("s")
        cache.extend("s", 0, jnp.zeros((10, 1, 2)),
                     jnp.zeros((10, 1, 2)))  # 5 pages
        cache.advance("s", 10)
        pt, _ = cache.batch_views(["s"])
        assert pt.shape[1] == 8  # 5 -> next pow2

    def test_views_reused_across_layers(self):
        rng = np.random.RandomState(0)
        cache = PagedKVCache(2, 8, 4, 2, 4)
        cache.add_sequence("s")
        for layer in range(2):
            cache.extend("s", layer,
                         jnp.asarray(rng.randn(4, 2, 4), jnp.float32),
                         jnp.asarray(rng.randn(4, 2, 4), jnp.float32))
        cache.advance("s", 4)
        views = cache.batch_views(["s"])
        q = jnp.asarray(rng.randn(1, 2, 4), jnp.float32)
        a0 = cache.attend(0, q, views=views)
        a1 = cache.attend(1, q, views=views)
        assert np.isfinite(np.asarray(a0)).all()
        assert not np.allclose(np.asarray(a0), np.asarray(a1))

    def test_empty_batch_clear_error(self):
        cache = PagedKVCache(1, 4, 2, 1, 2)
        with pytest.raises(ValueError, match="at least one"):
            cache.batch_views([])


class TestGPTPagedDecode:
    """Continuous-batching GPT decode over the shared page pool must
    produce the same logits as independent full forwards."""

    def _model(self):
        import paddle_tpu as paddle
        from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=64,
                        dropout=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        return m

    def test_matches_full_forward_ragged_batch(self):
        import paddle_tpu as paddle
        m = self._model()
        rng = np.random.RandomState(0)
        cache = m.make_paged_cache(n_pages=32, page_size=4)
        prompts = {"a": rng.randint(0, 64, (5,)),
                   "b": rng.randint(0, 64, (9,))}
        # ragged join: prefill each sequence separately
        logits = {}
        for sid, p in prompts.items():
            cache.add_sequence(sid)
            out = m.paged_decode_step(
                cache, [sid], paddle.to_tensor(p[None].astype(np.int64)))
            logits[sid] = out.numpy()[0]
        # one batched decode step with a new token per sequence
        nxt = {sid: int(l.argmax()) for sid, l in logits.items()}
        step_in = paddle.to_tensor(np.array(
            [[nxt["a"]], [nxt["b"]]], np.int64))
        out2 = m.paged_decode_step(cache, ["a", "b"], step_in).numpy()

        # oracle: full dense forward per sequence
        for i, sid in enumerate(["a", "b"]):
            full = np.concatenate([prompts[sid], [nxt[sid]]])
            ref = m(paddle.to_tensor(full[None].astype(np.int64)))
            np.testing.assert_allclose(
                out2[i], ref.numpy()[0, -1], rtol=1e-4, atol=1e-4)
            # and the prefill logits match the prompt-only forward
            ref_p = m(paddle.to_tensor(
                prompts[sid][None].astype(np.int64)))
            np.testing.assert_allclose(
                logits[sid], ref_p.numpy()[0, -1], rtol=1e-4, atol=1e-4)

    def test_sequence_leaves_batch(self):
        import paddle_tpu as paddle
        m = self._model()
        rng = np.random.RandomState(1)
        cache = m.make_paged_cache(n_pages=16, page_size=4)
        for sid in ("x", "y"):
            cache.add_sequence(sid)
            m.paged_decode_step(cache, [sid], paddle.to_tensor(
                rng.randint(0, 64, (1, 4)).astype(np.int64)))
        free_before = cache.n_free_pages()
        cache.free_sequence("x")
        assert cache.n_free_pages() > free_before
        # y keeps decoding alone
        out = m.paged_decode_step(cache, ["y"], paddle.to_tensor(
            np.array([[3]], np.int64)))
        assert np.isfinite(out.numpy()).all()
