"""Third sweep: fft hermitian family, signal frame/overlap_add,
ViterbiDecoder, Dirichlet/Multinomial distributions, matrix_rank —
numpy/scipy/torch oracles."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestFFTHermitian:
    def test_rfft2_matches_numpy(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 6).astype(np.float32)
        got = paddle.fft.rfft2(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, np.fft.rfft2(x), rtol=1e-4,
                                   atol=1e-5)

    def test_hfft_matches_numpy(self):
        rng = np.random.RandomState(0)
        x = (rng.randn(5) + 1j * rng.randn(5)).astype(np.complex64)
        got = paddle.fft.hfft(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, np.fft.hfft(x), rtol=1e-3,
                                   atol=1e-4)

    def test_ihfft_matches_numpy(self):
        rng = np.random.RandomState(0)
        x = rng.randn(8).astype(np.float32)
        got = paddle.fft.ihfft(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, np.fft.ihfft(x), rtol=1e-4,
                                   atol=1e-5)

    def test_irfft_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(10).astype(np.float32)
        back = paddle.fft.irfft(paddle.fft.rfft(paddle.to_tensor(x)),
                                n=10).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)


class TestSignalFraming:
    def test_frame_matches_manual(self):
        x = np.arange(10, dtype=np.float32)
        got = paddle.signal.frame(paddle.to_tensor(x), frame_length=4,
                                  hop_length=2).numpy()
        # frames along the last axis: [n_frames from hops]
        want = np.stack([x[i:i + 4] for i in range(0, 7, 2)], axis=-1)
        np.testing.assert_allclose(got, want)

    def test_overlap_add_inverts_frame_cola(self):
        rng = np.random.RandomState(0)
        x = rng.randn(16).astype(np.float32)
        fr = paddle.signal.frame(paddle.to_tensor(x), frame_length=4,
                                 hop_length=4)  # non-overlapping
        back = paddle.signal.overlap_add(fr, hop_length=4).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-6)

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1, 256).astype(np.float32)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=64,
                                  hop_length=16)
        back = paddle.signal.istft(spec, n_fft=64, hop_length=16).numpy()
        n = min(back.shape[-1], 256)
        np.testing.assert_allclose(back[0, 32:n - 32], x[0, 32:n - 32],
                                   rtol=1e-3, atol=1e-4)


class TestViterbi:
    def test_matches_brute_force(self):
        from paddle_tpu.text import ViterbiDecoder
        rng = np.random.RandomState(0)
        B, T, N = 2, 4, 3
        pot = rng.randn(B, T, N).astype(np.float32)
        trans = rng.randn(N, N).astype(np.float32)
        dec = ViterbiDecoder(paddle.to_tensor(trans),
                             include_bos_eos_tag=False)
        lengths = paddle.to_tensor(np.array([4, 4], np.int64))
        scores, paths = dec(paddle.to_tensor(pot), lengths)

        # brute force over all tag sequences
        import itertools
        for b in range(B):
            best, best_path = -1e30, None
            for seq in itertools.product(range(N), repeat=T):
                s = pot[b, 0, seq[0]]
                for t in range(1, T):
                    s += trans[seq[t - 1], seq[t]] + pot[b, t, seq[t]]
                if s > best:
                    best, best_path = s, seq
            np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                       rtol=1e-5)
            np.testing.assert_array_equal(paths.numpy()[b], best_path)


class TestDistributions:
    def test_dirichlet_stats(self):
        from paddle_tpu.distribution import Dirichlet
        conc = paddle.to_tensor(np.array([2.0, 3.0, 5.0], np.float32))
        d = Dirichlet(conc)
        np.testing.assert_allclose(d.mean.numpy(), [0.2, 0.3, 0.5],
                                   rtol=1e-5)
        s = d.sample([2000])
        assert s.shape == [2000, 3]
        np.testing.assert_allclose(s.numpy().sum(-1), np.ones(2000),
                                   rtol=1e-4)
        np.testing.assert_allclose(s.numpy().mean(0), [0.2, 0.3, 0.5],
                                   atol=0.03)
        # log_prob vs scipy
        from scipy.stats import dirichlet as sp_d
        x = np.array([0.3, 0.3, 0.4], np.float32)
        got = float(d.log_prob(paddle.to_tensor(x)).item())
        np.testing.assert_allclose(got, sp_d.logpdf(x, [2., 3., 5.]),
                                   rtol=1e-4)

    def test_multinomial_log_prob(self):
        from paddle_tpu.distribution import Multinomial
        from scipy.stats import multinomial as sp_m
        probs = np.array([0.2, 0.3, 0.5], np.float32)
        m = Multinomial(10, paddle.to_tensor(probs))
        x = np.array([2.0, 3.0, 5.0], np.float32)
        got = float(m.log_prob(paddle.to_tensor(x)).item())
        np.testing.assert_allclose(got, sp_m.logpmf(x, 10, probs),
                                   rtol=1e-4)
        s = m.sample([500])
        np.testing.assert_allclose(np.asarray(s.numpy()).sum(-1),
                                   np.full(500, 10.0), rtol=1e-6)


class TestLinalgTail:
    def test_matrix_rank(self):
        a = np.diag([1.0, 2.0, 0.0]).astype(np.float32)
        assert int(paddle.linalg.matrix_rank(
            paddle.to_tensor(a)).item()) == 2

    def test_cholesky_solve_matches_scipy(self):
        from scipy.linalg import cho_solve
        rng = np.random.RandomState(0)
        a = rng.randn(4, 4).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        L = np.linalg.cholesky(spd).astype(np.float32)
        b = rng.randn(4, 2).astype(np.float32)
        got = paddle.linalg.cholesky_solve(
            paddle.to_tensor(b), paddle.to_tensor(L),
            upper=False).numpy()
        want = cho_solve((L, True), b)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
