"""Always-on flight recorder: bounded rings of recent telemetry + a
crash/hang debug-bundle dump.

Parity inspiration: the reference framework's `nan_inf_utils` debug hooks
and the operational reality of PAPER.md's north star — at production
scale the questions that matter are *what was the process doing on a
timeline when it got slow* and *what state was it in when it crashed or
hung*. The span store (`statistic.py`) and metrics registry
(`monitor.py`) aggregate; this module additionally keeps the RAW tail:

- **spans** — every closed host span (name, start, duration, thread,
  nesting depth), the events `trace_export.py` renders into a Perfetto
  timeline;
- **samples** — every counter/gauge/histogram update (the counter
  tracks of the timeline: queue depth, prefetch depth, host.blocked_s);
- **records** — the per-step / per-batch JSONL records
  (`monitor.export_step`), kept even when no metrics file is configured;
- **events** — structured anomalies (`kind:"event"`: NaN detections,
  loss spikes, watchdog expiries, scheduler crashes).

All rings are `collections.deque(maxlen=...)`: appends are O(1),
lock-free (CPython deque appends are atomic), and steady-state cost is
negligible — the recorder is ON by default.

Debug bundles: with `PADDLE_TPU_DEBUG_DUMP=<dir>` set, `auto_install()`
(called at `import paddle_tpu`) arms three dump triggers —

- **uncaught exception** (`sys.excepthook` + `threading.excepthook`,
  chained to the previous hooks),
- **watchdog expiry** (`PADDLE_TPU_WATCHDOG_S=<n>`: no train-step
  heartbeat for n seconds → all-thread stack dump + bundle, process
  keeps running),
- **SIGQUIT** (dump and keep running — the hang-diagnosis signal) and
  **SIGTERM** (dump, then the previous/default handling proceeds).

Each trigger writes `<dir>/<reason>/` containing `MANIFEST.json`,
`ring.json` (the rings above), `metrics_tail.jsonl` (tail of
`PADDLE_TPU_METRICS_FILE`), `hlo/<tag>.txt` + `<tag>.cost.json` (HLO and
XLA cost analysis of every registered AOT executable — `jit/api.py`
registers each train-step/serving compile), `requests_tail.jsonl` +
`serve_state.json` (the serving observatory's recent terminal request
records and every live engine's load_report/pool_stats —
`serve_observatory.py`), one `<name>.json` per registered state
provider (e.g. `ckpt_state.json` — the checkpoint manager's
committed/in-flight view, `distributed/checkpoint.py`), `env.json`
(argv/versions/PADDLE*/JAX* env),
and `stacks.txt` (faulthandler all-thread stacks). Writing never
raises: a dump is diagnostics, not a second crash. See
docs/OBSERVABILITY.md "The flight recorder".

`paddle_tpu.distributed.launch` propagates `PADDLE_TPU_DEBUG_DUMP` with
a per-rank subdirectory and sets `PADDLE_TPU_SIGQUIT_STACKS=1` so a
multi-process hang is debuggable rank by rank (`kill -QUIT <pid>`).
"""
import collections
import faulthandler
import json
import os
import signal
import sys
import threading
import time
import traceback
import weakref

__all__ = ["record_span_event", "record_sample", "record_record",
           "record_event", "register_executable",
           "register_state_provider", "heartbeat",
           "snapshot", "reset", "dump", "install", "auto_install",
           "Watchdog", "perf_to_wall"]

# ring sizes: enough tail to reconstruct the last ~minutes of a step
# loop, small enough that a full snapshot serializes in milliseconds
SPAN_RING = 4096
SAMPLE_RING = 4096
RECORD_RING = 1024
EVENT_RING = 256
EXEC_REGISTRY = 8
_HLO_CAP = 4 << 20  # bytes of HLO text kept per executable in a bundle

# wall-clock anchor for the perf_counter timestamps spans carry:
# wall = perf + _PERF_TO_WALL (one process-wide offset; good enough to
# merge per-rank traces recorded on the same host)
_PERF_TO_WALL = time.time() - time.perf_counter()

_spans = collections.deque(maxlen=SPAN_RING)
_samples = collections.deque(maxlen=SAMPLE_RING)
_records = collections.deque(maxlen=RECORD_RING)
_events = collections.deque(maxlen=EVENT_RING)
_execs = collections.OrderedDict()  # tag -> weakref-or-strong compiled
_exec_lock = threading.Lock()

_beat = {"ts": None, "step": None, "count": 0}
_installed = {"hooks": False}
_watchdog = [None]
# name -> list of weakref-wrapped zero-arg callables returning a
# JSON-serializable payload; a debug bundle writes each name as
# <name>.json from the NEWEST LIVE provider (e.g. the checkpoint
# manager's ckpt_state.json — distributed/checkpoint.py registers it).
# Weak references: registration must not keep a dead manager (a
# bench/gate throwaway) alive, and once it's collected the previously
# registered live one shows through again.
_state_providers = {}


def perf_to_wall(t_perf):
    """Map a time.perf_counter() stamp onto unix seconds."""
    return t_perf + _PERF_TO_WALL


def record_span_event(name, t0_perf, dur_s, thread_ident, depth=0):
    """One CLOSED span (called by statistic.py when a span ends or an
    already-measured duration is recorded). t0_perf is the span's start
    on the perf_counter clock."""
    _spans.append((name, t0_perf, dur_s, thread_ident, depth))


def record_sample(name, kind, value):
    """One metric update (counter running total / gauge value /
    histogram observation) — a point on that metric's counter track."""
    try:
        _samples.append((time.time(), name, kind, float(value)))
    except (TypeError, ValueError):
        pass


def record_record(rec):
    """One exported JSONL record (step/scan/serve/health) — kept in the
    ring whether or not PADDLE_TPU_METRICS_FILE is set."""
    _records.append(rec)


def record_event(event, **fields):
    """One structured anomaly/lifecycle event. Lands in the events ring
    AND (when configured) the metrics JSONL as a `kind:"event"` record.
    Returns the record. Never raises."""
    rec = {"ts": time.time(), "event": str(event)}
    rec.update(fields)
    _events.append(rec)
    try:
        from . import monitor as _monitor
        _monitor.counter("flight.events").inc()
        _monitor.export_step({k: v for k, v in rec.items() if k != "ts"},
                             kind="event", _ring=False)
    except Exception:
        pass
    return rec


def register_executable(tag, compiled):
    """Remember a compiled XLA executable so a debug bundle can dump its
    HLO + cost analysis. Bounded (oldest evicted); holds a weakref when
    the object supports it so the registry never extends a dead train
    step's device memory."""
    try:
        ref = weakref.ref(compiled)
    except TypeError:
        ref = compiled  # strong fallback: owners cache these anyway
    with _exec_lock:
        _execs.pop(tag, None)
        _execs[tag] = ref
        while len(_execs) > EXEC_REGISTRY:
            _execs.popitem(last=False)


def register_state_provider(name, fn):
    """Register a zero-arg callable whose JSON-serializable return
    value a debug bundle writes as `<name>.json` (e.g. "ckpt_state" →
    the checkpoint manager's committed/queued/last-error view). Held
    via weakref (a bound method pins neither its owner nor the
    registry); per name the newest LIVE registration wins, and dead
    ones are pruned at dump time. Providers must never raise for the
    bundle to matter, but dump() guards them anyway."""
    try:
        ref = weakref.WeakMethod(fn)
    except TypeError:  # plain function/lambda: module-lived, hold it
        ref = (lambda f=fn: f)
    lst = _state_providers.setdefault(str(name), [])
    lst.append(ref)
    del lst[:-8]  # bounded per name


def _resolve_state_providers():
    """{name: newest live provider}, pruning dead weakrefs."""
    out = {}
    for name, lst in list(_state_providers.items()):
        lst[:] = [r for r in lst if r() is not None]
        if lst:
            out[name] = lst[-1]()
        else:
            _state_providers.pop(name, None)
    return out


def _live_executables():
    out = []
    with _exec_lock:
        items = list(_execs.items())
    for tag, ref in items:
        obj = ref() if isinstance(ref, weakref.ref) else ref
        if obj is not None:
            out.append((tag, obj))
    return out


def heartbeat(step=None):
    """Train-step liveness pulse (called once per dispatched step — a
    monotonic read and two stores; the watchdog measures hang time as
    the age of the last pulse)."""
    _beat["ts"] = time.monotonic()
    if step is not None:
        _beat["step"] = step
    _beat["count"] += 1


def snapshot():
    """The rings as plain JSON-serializable dicts (spans carry wall ts)."""
    spans = [{"name": n, "ts": perf_to_wall(t0), "dur_s": d,
              "tid": tid, "depth": depth}
             for (n, t0, d, tid, depth) in list(_spans)]
    samples = [{"ts": ts, "name": n, "kind": k, "value": v}
               for (ts, n, k, v) in list(_samples)]
    return {"spans": spans, "samples": samples,
            "records": list(_records), "events": list(_events),
            "heartbeat": dict(_beat),
            "executables": [tag for tag, _ in _live_executables()]}


def reset():
    """Drop ring contents (tests); handlers/registry stay installed."""
    _spans.clear()
    _samples.clear()
    _records.clear()
    _events.clear()
    _beat.update({"ts": None, "step": None, "count": 0})


# -- debug bundle --------------------------------------------------------

def _dump_dir():
    return os.environ.get("PADDLE_TPU_DEBUG_DUMP") or None


def _write_json(path, payload):
    try:
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        return True
    except Exception:
        return False


def dump(reason="manual", exc=None, base_dir=None):
    """Write a debug bundle into `<base>/<reason>/`; returns the bundle
    path or None when no dump dir is configured. NEVER raises — a dump
    runs inside excepthooks and signal handlers."""
    try:
        base = base_dir or _dump_dir()
        if not base:
            return None
        d = os.path.join(base, str(reason))
        os.makedirs(os.path.join(d, "hlo"), exist_ok=True)

        try:
            from . import monitor as _monitor
            rank = _monitor.rank()
            mfile = _monitor.metrics_file()
        except Exception:
            rank, mfile = 0, None

        manifest = {"schema": "paddle_tpu.debug_bundle.v1",
                    "reason": str(reason),
                    "ts": time.time(),
                    "recorded_utc": time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                    "rank": rank, "pid": os.getpid(),
                    "heartbeat": dict(_beat)}
        if exc is not None:
            manifest["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc)[:2000],
                "traceback": "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__))[-8000:]}

        # ring tail first — it is the part no other artifact carries
        _write_json(os.path.join(d, "ring.json"), snapshot())

        # all-thread stacks (faulthandler: signal-safe C-level dump)
        try:
            with open(os.path.join(d, "stacks.txt"), "w") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
        except Exception:
            pass

        # metrics JSONL tail
        if mfile:
            try:
                with open(mfile, errors="replace") as f:
                    tail = f.readlines()[-200:]
                with open(os.path.join(d, "metrics_tail.jsonl"), "w") as f:
                    f.writelines(tail)
            except Exception:
                pass

        # HLO + cost analysis of every registered AOT executable
        hlo_tags = []
        for tag, compiled in _live_executables():
            safe = "".join(c if c.isalnum() or c in "._-" else "_"
                           for c in tag)[:120]
            try:
                text = compiled.as_text()[:_HLO_CAP]
                with open(os.path.join(d, "hlo", safe + ".txt"), "w") as f:
                    f.write(text)
                hlo_tags.append(tag)
            except Exception:
                continue
            try:
                from . import cost as _cost
                _write_json(os.path.join(d, "hlo", safe + ".cost.json"),
                            _cost.cost_analysis(compiled))
            except Exception:
                pass
        manifest["hlo"] = hlo_tags

        # the compilation ledger: every compile this process ran, with
        # per-tag rollups — WHERE the compile seconds went, which
        # executables were cache hits, and the fusion/bytes-accessed
        # numbers the ratchet gates compare (compile_observatory.py)
        try:
            from . import compile_observatory as _obs
            recs = _obs.ledger()
            if recs:
                _write_json(os.path.join(d, "compile_ledger.json"),
                            {"records": recs,
                             "by_tag": _obs.aggregate(recs)})
                manifest["compile_records"] = len(recs)
        except Exception:
            pass

        # the serving observatory: recent terminal request records +
        # per-engine admission/pool state — a hung serving loop names
        # the requests in flight (docs/SERVING.md)
        try:
            from . import serve_observatory as _serve
            tail = _serve.requests_tail()
            if tail:
                with open(os.path.join(d, "requests_tail.jsonl"),
                          "w") as f:
                    for rec in tail:
                        f.write(json.dumps(rec, default=str) + "\n")
                manifest["request_records"] = len(tail)
            payload = _serve.debug_payload()
            if payload.get("engines") or tail:
                _write_json(os.path.join(d, "serve_state.json"), payload)
        except Exception:
            pass

        # the memory observatory: the full tag ledger, attribution
        # split, per-pool pool_stats, per-executable memory_analysis
        # peaks, and — after an OOM routed through oom_error — the
        # parsed request context. Written unconditionally when anything
        # is registered: an OOM post-mortem's first question is WHO
        # held the bytes (docs/OBSERVABILITY.md)
        try:
            from . import mem_observatory as _mem
            if _mem.registered_tags() or _mem.records_tail():
                _write_json(os.path.join(d, "mem_state.json"),
                            _mem.mem_state())
                manifest["mem_state"] = True
        except Exception:
            pass

        # registered state providers (ckpt_state.json, ...): subsystem
        # snapshots a post-mortem needs that no ring carries — e.g.
        # which checkpoints are committed vs in-flight when a wedged
        # step gets SIGTERMed (distributed/elastic.py watchdog)
        provided = []
        for name, fn in _resolve_state_providers().items():
            safe = "".join(c if c.isalnum() or c in "._-" else "_"
                           for c in name)[:80]
            try:
                if _write_json(os.path.join(d, safe + ".json"), fn()):
                    provided.append(name)
            except Exception:
                continue
        if provided:
            manifest["state_providers"] = provided

        # env / versions / argv
        envkeys = ("PADDLE", "JAX", "XLA", "TPU", "BENCH", "FLAGS_")
        env = {k: v for k, v in os.environ.items()
               if any(k.startswith(p) for p in envkeys)}
        versions = {"python": sys.version}
        for mod in ("jax", "jaxlib", "numpy"):
            m = sys.modules.get(mod)
            if m is not None:
                versions[mod] = getattr(m, "__version__", "?")
        pt = sys.modules.get("paddle_tpu")
        if pt is not None:
            versions["paddle_tpu"] = getattr(pt, "__version__", "?")
        _write_json(os.path.join(d, "env.json"),
                    {"argv": list(sys.argv), "cwd": os.getcwd(),
                     "env": env, "versions": versions, "rank": rank})

        _write_json(os.path.join(d, "MANIFEST.json"), manifest)
        record_event("debug_dump", reason=str(reason), path=d)
        return d
    except Exception:
        return None


# -- triggers ------------------------------------------------------------

class Watchdog:
    """Background hang detector: when no train-step heartbeat lands for
    `timeout_s`, write ONE debug bundle (reason "watchdog", all-thread
    stacks included) and keep the process running — the dump is the
    diagnosis, killing is the supervisor's call. The countdown starts at
    `start()` (so a hang *before* the first step — e.g. a wedged compile
    or backend init — still dumps) and resets on every heartbeat."""

    def __init__(self, timeout_s, base_dir=None):
        self.timeout_s = float(timeout_s)
        self.base_dir = base_dir
        self.fired = False
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        heartbeat()  # arm: countdown measured from now
        self._thread = threading.Thread(target=self._loop,
                                        name="flight-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        poll = max(0.05, min(1.0, self.timeout_s / 4.0))
        while not self._stop.wait(poll):
            last = _beat["ts"]
            if last is None:
                continue
            age = time.monotonic() - last
            if age >= self.timeout_s:
                record_event("watchdog_expired", hang_s=round(age, 3),
                             step=_beat["step"], timeout_s=self.timeout_s)
                dump("watchdog", base_dir=self.base_dir)
                self.fired = True  # after the dump: fired == bundle done
                return  # one-shot: no dump storms


def _chain_excepthook():
    prev = sys.excepthook

    def hook(etype, value, tb):
        if not issubclass(etype, (KeyboardInterrupt, SystemExit)):
            record_event("uncaught_exception", type=etype.__name__,
                         message=str(value)[:400])
            dump("exception", exc=value)
        prev(etype, value, tb)

    sys.excepthook = hook

    t_prev = getattr(threading, "excepthook", None)
    if t_prev is not None:
        def t_hook(args):
            if args.exc_type is not SystemExit:
                record_event("uncaught_thread_exception",
                             type=args.exc_type.__name__,
                             message=str(args.exc_value)[:400],
                             thread=getattr(args.thread, "name", "?"))
                dump("exception", exc=args.exc_value)
            t_prev(args)
        threading.excepthook = t_hook


def _install_signal_dumps():
    """SIGQUIT: dump and keep running (hang diagnosis). SIGTERM: dump,
    then hand the signal to whatever handling was there before (default
    = die), preserving launch/driver kill semantics."""
    try:
        def on_quit(signum, frame):
            record_event("sigquit")
            dump("sigquit")
        signal.signal(signal.SIGQUIT, on_quit)
    except (ValueError, OSError, AttributeError):
        pass  # non-main thread or platform without SIGQUIT

    try:
        prev_term = signal.getsignal(signal.SIGTERM)

        def on_term(signum, frame):
            record_event("sigterm")
            dump("sigterm")
            if prev_term is signal.SIG_IGN:
                return  # the process deliberately ignores SIGTERM:
                        # dump, but do NOT turn ignored into fatal
            if callable(prev_term):
                prev_term(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
        signal.signal(signal.SIGTERM, on_term)
    except (ValueError, OSError):
        pass


def install(base_dir=None, watchdog_s=None):
    """Arm the dump triggers (idempotent for the hook set). `base_dir`
    overrides PADDLE_TPU_DEBUG_DUMP; `watchdog_s` starts a Watchdog."""
    if base_dir:
        os.environ["PADDLE_TPU_DEBUG_DUMP"] = base_dir
    if not _installed["hooks"]:
        _installed["hooks"] = True
        _chain_excepthook()
        _install_signal_dumps()
    if watchdog_s and _watchdog[0] is None:
        _watchdog[0] = Watchdog(watchdog_s).start()
    return _watchdog[0]


def auto_install():
    """Called at `import paddle_tpu`: arm dumps when the operator asked
    for them via env — otherwise install NOTHING (no signal handlers, no
    threads; the rings alone are always on and cost nothing to arm)."""
    if _dump_dir():
        wd = os.environ.get("PADDLE_TPU_WATCHDOG_S")
        try:
            wd_s = float(wd) if wd else None
        except ValueError:
            wd_s = None
        install(watchdog_s=wd_s)
    elif os.environ.get("PADDLE_TPU_SIGQUIT_STACKS"):
        # launch.py workers: `kill -QUIT <pid>` dumps all-thread stacks
        # to stderr (the per-rank workerlog) without dying
        try:
            faulthandler.register(signal.SIGQUIT, all_threads=True,
                                  chain=True)
        except (ValueError, OSError, AttributeError):
            pass
