"""Distributed tests on the 8-virtual-device CPU mesh (SURVEY.md §4)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.env import build_mesh
from paddle_tpu.distributed.meta_parallel import (PipelineLayer,
                                                  PipelineParallel,
                                                  LayerDesc)
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no


def make_loss_fn():
    def loss_fn(out, y):
        return nn.functional.cross_entropy(
            out.reshape([-1, out.shape[-1]]), y.reshape([-1]))
    return loss_fn


class TestMesh:
    def test_build_mesh_axes(self):
        mesh = build_mesh(dp=2, mp=2, sharding=2)
        assert dict(mesh.shape) == {"dp": 2, "sharding": 2, "pp": 1,
                                    "mp": 2, "sp": 1, "ep": 1}

    def test_fleet_init_topology(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 4
        strategy.hybrid_configs["mp_degree"] = 2
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_data_parallel_world_size() == 4
        assert hcg.get_model_parallel_world_size() == 2


class TestHybridTrain:
    @pytest.mark.heavy
    def test_dp_mp_sharding_step(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 2
        strategy.hybrid_configs["mp_degree"] = 2
        strategy.hybrid_configs["sharding_degree"] = 2
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny())
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = fleet.build_train_step(m, make_loss_fn(), o)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
        l0 = step(ids, ids).item()
        for _ in range(3):
            l = step(ids, ids).item()
        assert l < l0
        pk = "gpt.h.0.attn.qkv_proj.weight"
        assert "mp" in str(step.params[pk].sharding.spec)
        assert "sharding" in str(step.opt_state[pk][0].sharding.spec)

    def test_collectives_in_hlo(self):
        """The compiled hybrid step must contain real cross-device
        collectives (dp grad psum / mp activity)."""
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 4
        strategy.hybrid_configs["mp_degree"] = 2
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny())
        o = opt.SGD(learning_rate=1e-3, parameters=m.parameters())
        step = fleet.build_train_step(m, make_loss_fn(), o)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
        hlo = step.compiled_text(ids, ids)
        assert "all-reduce" in hlo or "all-gather" in hlo or \
            "reduce-scatter" in hlo

    @pytest.mark.heavy
    def test_dp_matches_single_device(self):
        """dp=8 training must produce the same loss trajectory as a
        single-device run on the same global batch."""
        paddle.seed(0)
        m1 = GPTForCausalLM(gpt_tiny())
        sd = m1.state_dict()

        ids = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 1024, size=(8, 16)))
        from paddle_tpu.jit import TrainStep

        o1 = opt.SGD(learning_rate=0.01, parameters=m1.parameters())
        s1 = TrainStep(m1, make_loss_fn(), o1)
        seq = [s1(ids, ids).item() for _ in range(3)]

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 8
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m2 = GPTForCausalLM(gpt_tiny())
        m2.set_state_dict(sd)
        o2 = opt.SGD(learning_rate=0.01, parameters=m2.parameters())
        s2 = fleet.build_train_step(m2, make_loss_fn(), o2)
        par = [s2(ids, ids).item() for _ in range(3)]
        np.testing.assert_allclose(seq, par, rtol=1e-4, atol=1e-5)

    def test_grad_accumulation(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 2
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny())
        o = opt.SGD(learning_rate=1e-2, parameters=m.parameters())
        step = fleet.build_train_step(m, make_loss_fn(), o,
                                      accumulate_steps=2)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
        l0 = step(ids, ids).item()
        l1 = step(ids, ids).item()
        assert np.isfinite(l0) and l1 < l0


class TestPipeline:
    @pytest.mark.heavy
    def test_forward_parity_and_training(self):
        paddle.seed(0)
        mesh = build_mesh(dp=1, pp=4, mp=1, devices=jax.devices()[:4])
        pipe = PipelineLayer(
            [LayerDesc(nn.Linear, 16, 16) for _ in range(8)],
            num_stages=4, loss_fn=lambda o, y: ((o - y) ** 2).mean())
        o = opt.SGD(learning_rate=0.02, parameters=pipe.parameters())
        pp = PipelineParallel(pipe, o, mesh, n_micro=4)
        x = paddle.randn([8, 16])
        y = paddle.randn([8, 16])
        np.testing.assert_allclose(pp.forward(x).numpy(),
                                   pipe(x).numpy(), rtol=1e-4, atol=1e-5)
        l0 = pp.train_batch(x, y).item()
        for _ in range(10):
            l = pp.train_batch(x, y).item()
        assert l < l0

    def test_nonuniform_stages_rejected(self):
        pipe = PipelineLayer(
            [LayerDesc(nn.Linear, 16, 16), LayerDesc(nn.Linear, 16, 8),
             LayerDesc(nn.Linear, 8, 16), LayerDesc(nn.ReLU)],
            num_stages=2)
        o = opt.SGD(parameters=pipe.parameters())
        mesh = build_mesh(dp=1, pp=2, mp=1, devices=jax.devices()[:2])
        with pytest.raises(ValueError):
            PipelineParallel(pipe, o, mesh, n_micro=2)


class TestMPLayers:
    def test_column_row_roundtrip(self):
        from paddle_tpu.distributed.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear)
        paddle.seed(0)
        col = ColumnParallelLinear(8, 16, gather_output=False)
        row = RowParallelLinear(16, 8, input_is_parallel=True)
        x = paddle.randn([4, 8])
        out = row(col(x))
        assert out.shape == [4, 8]
        # eager equivalence to plain two-layer matmul
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
            @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_vocab_parallel_embedding(self):
        from paddle_tpu.distributed.meta_parallel import \
            VocabParallelEmbedding
        emb = VocabParallelEmbedding(100, 16)
        ids = paddle.to_tensor(np.array([[1, 5], [7, 99]]))
        assert emb(ids).shape == [2, 2, 16]


class TestRecompute:
    def test_recompute_matches_plain(self):
        from paddle_tpu.distributed.fleet.utils.recompute import recompute
        paddle.seed(0)
        lin = nn.Linear(8, 8)
        x = paddle.randn([4, 8])

        from paddle_tpu.jit.api import functional_call, state_arrays
        params, _ = state_arrays(lin)

        def with_remat(ps):
            def f(p):
                def inner(xx):
                    return functional_call(lin, p, {}, (xx,))
                return jax.checkpoint(inner)(x.value).sum()
            return f(ps)

        def plain(ps):
            return functional_call(lin, ps, {}, (x.value,)).sum()

        g1 = jax.grad(with_remat)(params)
        g2 = jax.grad(plain)(params)
        for k in params:
            np.testing.assert_allclose(np.asarray(g1[k]),
                                       np.asarray(g2[k]), rtol=1e-5)


class TestAutoParallel:
    def test_shard_tensor(self):
        from paddle_tpu.distributed import shard_tensor, ProcessMesh
        mesh = ProcessMesh(shape=(4, 2), dim_names=["x", "y"])
        t = paddle.ones([8, 4])
        shard_tensor(t, mesh, ["x", None])
        assert "x" in str(t.value.sharding.spec)


class TestCollectivesAPI:
    def test_spmd_psum(self):
        from paddle_tpu.distributed import psum
        from jax.sharding import PartitionSpec as P
        mesh = build_mesh(dp=8)

        def f(x):
            return psum(x, "dp")
        from paddle_tpu.framework.jax_compat import shard_map
        out = shard_map(f, mesh=mesh, in_specs=P("dp"),
                        out_specs=P())(jnp.arange(8.0))
        assert float(out[0]) == 28.0

    def test_eager_api_parity(self):
        import paddle_tpu.distributed as dist
        t = paddle.ones([4])
        dist.all_reduce(t)
        lst = []
        dist.all_gather(lst, t)
        assert len(lst) == 1
        dist.broadcast(t, 0)
        assert dist.get_world_size() == 8


class TestZeROStages:
    """Real ZeRO stage-2/3 behavior (ref sharding_stage2.py:43,
    sharding_stage3.py:51): stage selection changes the compiled program
    (reduce-scatter / sharded param storage) without changing numerics."""

    def _build(self, stage, lr=0.01):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 4
        strategy.hybrid_configs["sharding_degree"] = 2
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny())
        o = opt.AdamW(learning_rate=lr, parameters=m.parameters())
        return fleet.build_train_step(m, make_loss_fn(), o,
                                      sharding_stage=stage)

    @pytest.mark.heavy

    def test_stage2_grads_constrained_sharded(self):
        """Stage-2 pins gradients to the 'sharding' axis: the lowered
        program must carry the sharding constraints (28 grad leaves), and
        the compiled update must run on grad SHARDS (sliced shapes), with
        the grad sync lowered as all-reduce+slice — the pair the TPU
        ReduceScatterCreator pass fuses into reduce-scatter (the CPU
        pipeline keeps them separate, so we assert the pattern, not the
        fused op name)."""
        import jax.numpy as jnp
        from paddle_tpu.framework.random import split_key
        step = self._build(2)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
        arrays = [ids.value, ids.value]
        lowered = step._jitted.lower(
            step.params, step.opt_state, step.scaler_state, step.buffers,
            split_key(), jnp.asarray(0.1, jnp.float32), 1, *arrays)
        txt = lowered.as_text()
        # jax >= 0.6 prints sharding_constraint ops; 0.4.x lowers the
        # same constraint as a custom_call @Sharding
        n_constraints = txt.count("sharding_constraint") + \
            txt.count("@Sharding")
        assert n_constraints >= 20, n_constraints
        hlo = lowered.compile().as_text()
        # qkv grad [64,192] over sharding=2 -> update math sees [32,192]
        assert "f32[32,192]" in hlo, "update does not run on grad shards"
        assert ("reduce-scatter" in hlo) or ("all-reduce" in hlo)

    @pytest.mark.heavy

    def test_stage3_params_stored_sharded(self):
        step = self._build(3)
        pk = "gpt.h.0.attn.qkv_proj.weight"
        assert "sharding" in str(step.params[pk].sharding.spec)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
        hlo = step.compiled_text(ids, ids)
        assert "all-gather" in hlo, "stage-3 must all-gather params at use"

    @pytest.mark.heavy
    def test_stages_numerics_match(self):
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
        losses = {}
        for stage in (1, 2, 3):
            step = self._build(stage)
            losses[stage] = [step(ids, ids).item() for _ in range(3)]
        np.testing.assert_allclose(losses[1], losses[2], rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(losses[1], losses[3], rtol=1e-4,
                                   atol=1e-5)

    @pytest.mark.heavy

    def test_wrappers_select_behavior(self):
        """ShardingStage3(layer) marker must flow into the train step."""
        from paddle_tpu.distributed.meta_parallel.sharding.sharding_stage \
            import ShardingStage3
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 4
        strategy.hybrid_configs["sharding_degree"] = 2
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = ShardingStage3(GPTForCausalLM(gpt_tiny()))
        o = opt.AdamW(learning_rate=0.01, parameters=m.parameters())
        step = fleet.build_train_step(m, make_loss_fn(), o)
        assert step.sharding_stage == 3
        pk = "gpt.h.0.attn.qkv_proj.weight"
        assert "sharding" in str(step.params[pk].sharding.spec)


class TestAutoParallel:
    """shard_tensor/shard_op/Planner (ref auto_parallel/interface.py:34,73
    + planner.py — GSPMD propagation is the TPU-native planner)."""

    def test_shard_op_constrains_inputs_and_outputs(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed.auto_parallel import (shard_op,
                                                          ProcessMesh)
        pm = ProcessMesh(shape=(8,), dim_names=["x"])

        def matmul(a, b):
            return a @ b

        sharded = shard_op(matmul, pm, in_shard_specs=[P("x", None), None],
                           out_shard_specs=P("x", None))

        def f(a, b):
            return sharded(a, b)

        a = jnp.ones((16, 8))
        b = jnp.ones((8, 4))
        out = jax.jit(f)(a, b)
        np.testing.assert_allclose(np.asarray(out), 8.0 * np.ones((16, 4)))
        txt = jax.jit(f).lower(a, b).as_text()
        assert "sharding" in txt  # constraints present in the program

    def test_planner_assigns_shardings(self):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed.auto_parallel import plan, ProcessMesh
        pm = ProcessMesh(shape=(8,), dim_names=["dp"])

        def step(x, w):
            return jnp.tanh(x @ w).sum()

        x = jnp.ones((32, 16))
        w = jnp.ones((16, 16))
        result = plan(step, x, w, process_mesh=pm,
                      in_specs=[P("dp", None), None])
        ins = result.input_shardings
        assert ins is not None
        out = result(x, w)
        np.testing.assert_allclose(float(np.asarray(out)),
                                   float(np.tanh(16.0) * 32 * 16))


class TestSequenceParallel:
    """Sequence-parallel GPT training through fleet: seq dim sharded over
    'sp', attention as ring attention (exact) — long-context first-class
    (SURVEY §6). Loss must match the non-sp run bit-for-bit-ish."""

    def _run(self, sep_degree, sequence_parallel, dp=2):
        from paddle_tpu.models.gpt import GPTConfig
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = dp
        strategy.hybrid_configs["sep_degree"] = sep_degree
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=64,
                        dropout=0.0, sequence_parallel=sequence_parallel)
        m = GPTForCausalLM(cfg)
        o = opt.SGD(learning_rate=0.01, parameters=m.parameters())
        step = fleet.build_train_step(m, make_loss_fn(), o)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 256, size=(8, 32)))
        return step, [step(ids, ids).item() for _ in range(2)]

    @pytest.mark.heavy
    def test_ring_matches_dense(self):
        _, base = self._run(sep_degree=1, sequence_parallel=False, dp=2)
        _, ring = self._run(sep_degree=4, sequence_parallel=True, dp=2)
        np.testing.assert_allclose(base, ring, rtol=1e-4, atol=1e-5)

    @pytest.mark.heavy
    def test_seq_dim_sharded_and_ring_in_hlo(self):
        step, _ = self._run(sep_degree=4, sequence_parallel=True, dp=2)
        assert "sp" in str(step.batch_sharding.spec)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 256, size=(8, 32)))
        hlo = step.compiled_text(ids, ids)
        assert "collective-permute" in hlo, "ring hops must be ppermute"


class TestFleetPipelineRouting:
    """fleet.build_train_step must route PipelineLayer models to the
    PipelineParallel engine (ref fleet.distributed_model wrap) and refuse
    pp_degree>1 for plain layers instead of silently replicating."""

    def test_pipeline_layer_routed(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["pp_degree"] = 4
        strategy.pipeline_configs["accumulate_steps"] = 4
        strategy.pipeline_configs["schedule_mode"] = "1F1B"
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        pipe = PipelineLayer(
            [LayerDesc(nn.Linear, 16, 16) for _ in range(4)],
            num_stages=4, loss_fn=lambda o, y: ((o - y) ** 2).mean())
        o = opt.SGD(learning_rate=0.02, parameters=pipe.parameters())
        step = fleet.build_train_step(pipe, None, o)
        assert step.engine.schedule == "1f1b"
        x = paddle.randn([8, 16])
        y = paddle.randn([8, 16])
        l0 = step(x, y).item()
        for _ in range(5):
            l = step(x, y).item()
        assert np.isfinite(l) and l < l0

    def test_plain_layer_with_pp_rejected(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["pp_degree"] = 2
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny())
        o = opt.SGD(learning_rate=0.01, parameters=m.parameters())
        with pytest.raises(ValueError, match="PipelineLayer"):
            fleet.build_train_step(m, make_loss_fn(), o)

    def test_stage_mesh_mismatch_rejected(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["pp_degree"] = 2
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        pipe = PipelineLayer(
            [LayerDesc(nn.Linear, 8, 8) for _ in range(4)],
            num_stages=4, loss_fn=lambda o, y: ((o - y) ** 2).mean())
        o = opt.SGD(parameters=pipe.parameters())
        with pytest.raises(ValueError, match="pp"):
            fleet.build_train_step(pipe, None, o)


class TestAutoParallelPlanner:
    """Measured planner (VERDICT r3 #8): plan(search=True) must pick a
    sharded input layout over replicated for a big matmul — ranked by
    XLA's own cost_analysis, the role of the reference's
    auto_parallel/planner.py + cost_model.py."""

    def test_search_picks_sharded_over_replicated(self):
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.distributed.auto_parallel import Planner

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
        planner = Planner(mesh)
        a = jnp.ones((1024, 512), jnp.float32)
        b = jnp.ones((512, 256), jnp.float32)

        result = planner.plan(lambda x, y: x @ y, a, b, search=True)
        # the chosen plan shards at least one operand over dp
        assert any("dp" in str(s) for s in result.chosen_specs), \
            result.chosen_specs
        # and beats fully-replicated in the measured ranking
        rep_cost = dict((tuple(str(x) for x in specs), c)
                        for specs, c in result.search_report)
        rep_key = (str(P()), str(P()))
        assert rep_key in rep_cost
        best_specs, best_cost = result.search_report[0]
        assert best_cost < rep_cost[rep_key], result.search_report[:3]
        # the winning plan actually executes
        out = result(a, b)
        np.testing.assert_allclose(np.asarray(out)[:2, :2], 512.0)
