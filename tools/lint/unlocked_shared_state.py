"""unlocked-shared-state pass: fields mutated on a background thread
and read elsewhere with no lock in scope.

The PR 10-12 hand-review catalog's "dict changed size during an
unlocked snapshot" class: the scheduler thread mutates `self._stats`
while `load_report()` iterates it from the caller's thread. This pass
finds the shape statically, per class:

1. **Thread contexts** — methods handed to `threading.Thread(target=
   ...)`, `executor.submit(...)`, or `add_done_callback(...)`
   anywhere in the file, plus everything reachable from them through
   same-class `self.m()` / same-module calls (intra-file closure).
2. **Access inventory** — every `self.<attr>` write (assign, augment,
   subscript store, known mutator calls: append/pop/update/clear/...)
   and read, tagged with the SET of lock identities lexically held
   (or a wildcard when the containing method is only ever called from
   under a lock — locked-context propagation; thread entries never
   qualify: the Thread start is a lock-free call site).
3. **Verdict** — `unlocked-shared-write`: a write and a cross-
   boundary access with NO COMMON lock. Identity matters: a writer
   under lock A and a reader under lock B race exactly like unlocked
   code — disjoint locks do not exclude each other. The finding
   cites both sites (and both locksets in the mismatch case).

Exemptions by construction (not suppressions):

- `__init__` writes — they happen-before the thread starts;
- attributes whose every post-init write is a plain CONSTANT assign
  (`self._stop = True`): the GIL makes the flag handoff atomic, and
  fencing every stop flag would bury the real findings;
- attributes never accessed outside the thread context (thread-local
  by usage).

False positives (e.g. a read that provably happens after `join()`)
take `# lint-ok[unlocked-shared-state]: <why>` on the access line.
"""
import ast

from .core import Finding, _BUILTIN_METHOD_NAMES, _last_attr

PASS_NAME = "unlocked-shared-state"

_MUTATORS = {"append", "appendleft", "pop", "popleft", "update",
             "clear", "extend", "add", "remove", "discard", "insert",
             "setdefault", "rotate", "sort"}


def _thread_entries(sf):
    """Callable names handed to Thread(target=...)/submit/
    add_done_callback in this file: {'Class.method' | 'func'}."""
    entries = set()
    if sf.tree is None:
        return entries

    def callable_name(node, cls):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and cls:
            return f"{cls}.{node.attr}"
        if isinstance(node, ast.Name):
            return node.id
        return None

    def visit(node, cls):
        if isinstance(node, ast.Call):
            last = _last_attr(node.func)
            if last == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        n = callable_name(kw.value, cls)
                        if n:
                            entries.add(n)
            elif last in ("submit", "add_done_callback"):
                if node.args:
                    n = callable_name(node.args[0], cls)
                    if n:
                        entries.add(n)
        for child in ast.iter_child_nodes(node):
            visit(child, node.name if isinstance(node, ast.ClassDef)
                  else cls)

    visit(sf.tree, None)
    return entries


#: wildcard lockset member for locked-context methods — the callers
#: hold SOME lock, identity unknown; matches any lock (conservative:
#: never fabricates a mismatch finding)
_ANY_LOCK = "<caller>"


class _Access:
    __slots__ = ("attr", "method", "line", "write", "mutation",
                 "locks", "const_assign")

    def __init__(self, attr, method, line, write, mutation, locks,
                 const_assign):
        self.attr = attr
        self.method = method
        self.line = line
        self.write = write
        self.mutation = mutation
        self.locks = locks  # frozenset of held lock ids (may be empty)
        self.const_assign = const_assign

    @property
    def locked(self):
        return bool(self.locks)


def _protected(a, b):
    """Two accesses are mutually protected only by a COMMON lock (or
    when either side's lockset is the locked-context wildcard)."""
    if _ANY_LOCK in a.locks or _ANY_LOCK in b.locks:
        return True
    return bool(a.locks & b.locks)


def _is_const(node):
    return isinstance(node, ast.Constant) or (
        isinstance(node, ast.UnaryOp) and
        isinstance(node.operand, ast.Constant))


class UnlockedSharedStatePass:
    name = PASS_NAME

    def run(self, ctx):
        ctx.build_summaries()
        findings = []
        for sf in ctx.files:
            if sf.tree is None:
                continue
            findings.extend(self._check_file(ctx, sf))
        return findings

    # -- per-file ----------------------------------------------------

    def _check_file(self, ctx, sf):
        entries = _thread_entries(sf)
        if not entries:
            return []
        infos = {info.qualname: info
                 for info in ctx.functions.values()
                 if info.file is sf}
        edges = self._call_edges(sf, infos)
        entry_quals = {q for q in infos
                       if q in entries or q.split(".")[-1] in entries}
        thread_ctx = self._closure(infos, entry_quals, edges)
        locked_ctx = self._locked_contexts(infos, entry_quals, edges)
        accesses = []
        for qual, info in infos.items():
            if info.class_name is None or \
                    qual.endswith("__init__"):
                continue
            accesses.extend(self._collect_accesses(
                ctx, sf, info, locked=qual in locked_ctx))
        bases = ctx._class_bases.get(sf.rel, {})
        return self._verdicts(sf, accesses, thread_ctx, bases)

    @staticmethod
    def _call_edges(sf, infos):
        """{caller_qual: [(callee_qual, held_bool)]} intra-file call
        edges, shared by the thread-context closure and the locked-
        context propagation.

        Unresolved `obj.m()` calls expand to EVERY same-file method
        named `m`: resolve_call's unique-definition ladder returns
        None when two classes define the name (serving.py — BOTH
        engines define `_loop_once`/`_outstanding`, and the shared
        `_SchedulerLifecycle.drain` calls them through `self`), and
        dropping those edges leaves the scheduler loops out of the
        thread context AND starves the locked-context propagation of
        the under-lock call sites that protect the readers. The
        expansion never claims builtin-shadowing or dunder names
        (same guard as resolve_call's fallback)."""
        by_name = {}
        for q in infos:
            if "." in q:
                by_name.setdefault(q.split(".")[-1], []).append(q)
        edges = {}
        for qual, info in infos.items():
            out = edges.setdefault(qual, [])
            for callee, held, _, label in info.calls:
                if callee and callee.startswith(f"{sf.rel}:"):
                    cq = callee.split(":", 1)[1]
                    if cq in infos:
                        out.append((cq, bool(held)))
                elif callee is None and "." in label:
                    last = label.rsplit(".", 1)[-1]
                    if last.startswith("__") or \
                            last in _BUILTIN_METHOD_NAMES:
                        continue
                    for cq in by_name.get(last, ()):
                        out.append((cq, bool(held)))
        return edges

    @staticmethod
    def _closure(infos, entry_quals, edges):
        """Thread context = entry callables + intra-file functions
        reachable from them through the call edges. Over-approximating
        the context is safe for this pass: a method wrongly inside it
        only tightens what counts as cross-boundary, it cannot
        suppress a finding on code that really races."""
        work = list(entry_quals)
        seen = set(work)
        while work:
            qual = work.pop()
            for cq, _ in edges.get(qual, ()):
                if cq not in seen:
                    seen.add(cq)
                    work.append(cq)
        return seen

    @staticmethod
    def _locked_contexts(infos, entry_quals, edges):
        """Methods whose EVERY intra-file call site holds a lock (or
        sits in an already-locked context): their bodies inherit the
        callers' protection. Thread ENTRIES never qualify — the
        Thread(target=...) start runs them lock-free and that call
        site is invisible to the intra-file scan. NON-entry methods
        the thread reaches DO qualify: their thread-side call sites
        are ordinary visible calls, so `all sites hold a lock`
        already accounts for them (a scheduler helper invoked only
        under the engine lock is protected, wherever the caller
        runs)."""
        call_sites = {}  # qualname -> [(caller_qual, held_bool)]
        for qual, outs in edges.items():
            for cq, held in outs:
                call_sites.setdefault(cq, []).append((qual, held))
        locked = set()
        changed = True
        while changed:
            changed = False
            for qual in infos:
                if qual in locked or qual not in call_sites or \
                        qual in entry_quals:
                    continue
                sites = call_sites[qual]
                if sites and all(held or caller in locked
                                 for caller, held in sites):
                    locked.add(qual)
                    changed = True
        return locked

    def _collect_accesses(self, ctx, sf, info, locked):
        out = []
        base = frozenset((_ANY_LOCK,)) if locked else frozenset()
        track_explicit = ".acquire(" in sf.text

        def add(attr, line, write, mutation, locks, const):
            out.append(_Access(attr, info.qualname, line, write,
                               mutation, locks, const))

        def walk(node, held):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node is not info.node:
                return
            new_held = held
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = ctx.lock_id(sf, item.context_expr,
                                      info.class_name, info.qualname)
                    if lid:
                        new_held = new_held | {lid}
            is_locked = base | held
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._target_accesses(t, node.value, add,
                                          is_locked, node.lineno)
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None:
                # `self._x: int = v` writes exactly like `self._x = v`;
                # a bare annotation (value None) declares, not writes
                self._target_accesses(node.target, node.value, add,
                                      is_locked, node.lineno)
            elif isinstance(node, ast.AugAssign):
                t = node.target
                if self._self_attr(t):
                    add(t.attr, node.lineno, True, False, is_locked,
                        False)
                elif isinstance(t, ast.Subscript) and \
                        self._self_attr(t.value):
                    add(t.value.attr, node.lineno, True, True,
                        is_locked, False)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            self._self_attr(t.value):
                        add(t.value.attr, node.lineno, True, True,
                            is_locked, False)
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in _MUTATORS and \
                        self._self_attr(f.value):
                    add(f.value.attr, node.lineno, True, True,
                        is_locked, False)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load) and \
                    self._self_attr(node):
                add(node.attr, node.lineno, False, False, is_locked,
                    False)
            # same sequential explicit-acquire flow as
            # core._summarize: a bounded `.acquire(timeout=)` region
            # protects the accesses inside it
            run = new_held
            for child in ast.iter_child_nodes(node):
                walk(child, run)
                if track_explicit:
                    acq, rel = ctx.lock_flow(sf, child,
                                             info.class_name,
                                             info.qualname)
                    if acq or rel:
                        run = (run - rel) | (acq - rel)

        walk(info.node, frozenset())
        return out

    @staticmethod
    def _self_attr(node):
        return isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self"

    def _target_accesses(self, target, value, add, is_locked, line):
        if self._self_attr(target):
            add(target.attr, line, True, False, is_locked,
                _is_const(value))
        elif isinstance(target, ast.Subscript) and \
                self._self_attr(target.value):
            add(target.value.attr, line, True, True, is_locked, False)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._target_accesses(el, ast.Constant(value=None),
                                      add, is_locked, line)

    # -- verdicts ----------------------------------------------------

    @staticmethod
    def _ancestors(bases, cls):
        out, work = set(), [cls]
        while work:
            c = work.pop()
            for b in bases.get(c, ()):
                if b in bases and b not in out:
                    out.add(b)
                    work.append(b)
        return out

    def _related(self, bases, m1, m2):
        """Two accesses share an instance only when their classes are
        the same or inheritance-related (same file): pairing
        `GenerationEngine.retraces` writes with `InferenceEngine`
        reads would report a race between two DIFFERENT objects'
        fields that merely share a name."""
        c1, c2 = m1.split(".")[0], m2.split(".")[0]
        return c1 == c2 or c1 in self._ancestors(bases, c2) or \
            c2 in self._ancestors(bases, c1)

    def _verdicts(self, sf, accesses, thread_ctx, bases):
        by_attr = {}
        for a in accesses:
            by_attr.setdefault(a.attr, []).append(a)
        findings = []
        for attr, accs in sorted(by_attr.items()):
            thread = [a for a in accs if a.method in thread_ctx]
            main = [a for a in accs if a.method not in thread_ctx]
            if not thread or not main:
                continue  # never shared across the boundary
            writes = [a for a in accs if a.write]
            if writes and all(a.const_assign for a in writes
                              if not a.mutation) and \
                    not any(a.mutation for a in writes):
                continue  # constant-flag handoff (GIL-atomic)
            # a (write, access) pair across the thread boundary is
            # safe only under a COMMON lock — writer under lock A and
            # reader under lock B is the same race as no lock at all.
            # Every distinct unprotected WRITE site reports (one
            # finding per anchor line): collapsing an attribute to its
            # first pair would let a line-scoped `# lint-ok` on that
            # pair silently exempt every OTHER racy site on the same
            # attribute
            pairs = self._unprotected_pairs(
                [a for a in thread if a.write], main, bases) + \
                self._unprotected_pairs(
                    [a for a in main if a.write], thread, bases)
            anchored = set()
            for w, r in pairs:
                w_side = "thread context " if w.method in thread_ctx \
                    else ""
                if w.locks and r.locks:
                    how = (f"under DIFFERENT locks "
                           f"({', '.join(sorted(w.locks))} vs "
                           f"{', '.join(sorted(r.locks))}) — disjoint "
                           "locks do not exclude each other")
                else:
                    how = "with no common lock held"
                # anchor at the UNLOCKED side — that's where the lock
                # is missing, and where a justified `# lint-ok`
                # belongs (write side when both are bare)
                anchor = w if not w.locks else r
                if anchor.line in anchored:
                    continue
                anchored.add(anchor.line)
                findings.append(Finding(
                    PASS_NAME, "unlocked-shared-write", sf.rel,
                    anchor.line,
                    f"self.{attr} written in {w_side}{w.method} "
                    f"({sf.rel}:{w.line}) and accessed from "
                    f"{r.method} ({sf.rel}:{r.line}) {how} — "
                    "snapshot/iterate races the mutation"))
        return findings

    def _unprotected_pairs(self, writes, accesses, bases):
        """One (write, access) pair per distinct unprotected write
        site: for each write (deduped by line) the first access on the
        SAME instance (classes inheritance-related) not protected by a
        common lock."""
        out, seen = [], set()
        for w in writes:
            if w.line in seen:
                continue
            for r in accesses:
                if self._related(bases, w.method, r.method) and \
                        not _protected(w, r):
                    out.append((w, r))
                    seen.add(w.line)
                    break
        return out
