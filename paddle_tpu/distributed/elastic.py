"""Elastic / fault-tolerant training controller.

Parity: python/paddle/distributed/elastic/ (+ the fleet elastic agent).
The reference's agent watches etcd for scale events and restarts ranks.
TPU-native failure model: a preempted/evicted host kills the whole SPMD
program, so recovery = the scheduler relaunches the job and the job
resumes from the newest COMMITTED checkpoint. `ElasticController`
packages that contract on top of `distributed.checkpoint.
CheckpointManager` (snapshot-then-write async saves, atomic commits,
verified resume — docs/FAULT_TOLERANCE.md):

    ctl = ElasticController(step, ckpt_dir, save_every_steps=500)
    start = ctl.maybe_resume()          # newest VERIFIED checkpoint
    ctl.start_watchdog()
    for batch in loader[start:]:
        loss = step(*batch)
        ctl.on_step()                   # never blocks the step loop

`on_step()` feeds the watchdog and, on the save cadence, snapshots the
training state on device and hands it to the background writer — the
step loop never waits on the previous write (the writer serializes
queued saves itself; a still-busy writer SKIPS the new save rather
than stacking snapshots). Step 0 is never saved (there is nothing to
resume to that a fresh init doesn't give).

The watchdog detects a wedged step (no `on_step()` progress within
`watchdog_timeout_s`): it first dumps a full flight-recorder debug
bundle — all-thread stacks, telemetry rings, registered HLO, and the
checkpoint manager's `ckpt_state.json` — and only THEN raises SIGTERM
for the scheduler to restart the process, so the hang is diagnosable
post-mortem.
"""
import os
import signal
import threading
import time

from .checkpoint import CheckpointManager
from ..profiler import flight_recorder as _flight
from ..profiler import monitor as _monitor

__all__ = ["ElasticController"]


class ElasticController:
    def __init__(self, train_step, ckpt_dir, save_every_steps=500,
                 watchdog_timeout_s=1800, keep_last=3, keep_every=None):
        self.step_obj = train_step
        self.ckpt_dir = ckpt_dir
        self.save_every = max(1, int(save_every_steps))
        self.timeout = watchdog_timeout_s
        self.manager = CheckpointManager(ckpt_dir, keep_last=keep_last,
                                         keep_every=keep_every)
        self._last_progress = time.time()
        self._last_saved = None
        self._watchdog = None
        self._stop = threading.Event()

    # -- resume --------------------------------------------------------
    def maybe_resume(self):
        """Restore the newest VERIFIED checkpoint if one exists
        (falling back past partial/corrupt ones); returns the resumed
        step (0 when starting fresh)."""
        restored = self.manager.restore(self.step_obj)
        self._last_progress = time.time()  # lint-ok[unlocked-shared-state]: GIL-atomic float heartbeat; the watchdog thread tolerates a stale read by design (it re-checks every timeout/4)
        if restored is not None:
            # resuming exactly onto a save boundary must not re-save it
            self._last_saved = restored
            return restored
        return 0

    def latest(self):
        """Path of the newest committed checkpoint, or None."""
        return self.manager.latest()

    # -- per-step hook (hot path: must never block) ---------------------
    def on_step(self):
        """Call after each train step: feeds the watchdog and saves on
        the cadence. Non-blocking — the snapshot is an async on-device
        copy and the write happens on the background writer thread; a
        writer still busy with the previous checkpoint skips this save
        instead of queueing snapshots."""
        self._last_progress = time.time()  # lint-ok[unlocked-shared-state]: GIL-atomic float heartbeat, same contract as the maybe_resume stamp — the watchdog tolerates staleness
        s = int(self.step_obj._step_i)
        if s > 0 and s % self.save_every == 0 and s != self._last_saved:
            self._last_saved = s
            self.manager.save(self.step_obj, step=s, skip_if_busy=True)

    def wait(self, timeout=None):
        """Drain pending checkpoint writes (tests / clean shutdown)."""
        self.manager.wait(timeout)

    # -- watchdog ------------------------------------------------------
    def start_watchdog(self):
        """Arm the wedged-step detector: when no on_step() lands within
        `watchdog_timeout_s`, dump a debug bundle (stacks + rings + HLO
        + ckpt_state.json, flight_recorder.dump) and SIGTERM this
        process so the scheduler restarts it — which resumes from the
        last committed checkpoint via maybe_resume()."""
        def run():
            while not self._stop.wait(min(self.timeout / 4, 60)):
                hang = time.time() - self._last_progress
                if hang > self.timeout:
                    _flight.record_event(
                        "elastic_watchdog_expired",
                        hang_s=round(hang, 3),
                        step=int(getattr(self.step_obj, "_step_i", -1)),
                        timeout_s=self.timeout)
                    _monitor.counter("ckpt.watchdog_fired").inc()
                    # diagnosis BEFORE the kill: the bundle (when
                    # PADDLE_TPU_DEBUG_DUMP is set) carries the stacks
                    # and checkpoint state of the wedged process
                    _flight.dump("elastic_watchdog")
                    os.kill(os.getpid(), signal.SIGTERM)
                    return
        self._watchdog = threading.Thread(target=run, daemon=True,
                                          name="elastic-watchdog")
        self._watchdog.start()

    def stop(self):
        self._stop.set()
        self.manager.wait()
