"""ParallelCrossEntropy: gather-free, mp-sharded softmax cross-entropy.

The mechanism under test (mp_layers.py -> ops/chunked_xent.py
softmax_xent_logits): an explicit 'mp' sharding constraint pins the
vocab dim to the mesh and the gold logit is a one-hot product-sum, so
the lowered SPMD program reduces partial max/sum per shard — it must
NEVER all-gather the full-vocab logits (the largest tensor of an LM
step), and it must match the plain cross-entropy numerics exactly.
"""
import re

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.core import Tensor
from paddle_tpu.distributed.env import build_mesh, set_mesh, _state
from paddle_tpu.distributed.meta_parallel.parallel_layers.mp_layers \
    import ParallelCrossEntropy
from paddle_tpu.ops.chunked_xent import softmax_xent_logits

N, V = 64, 512


@pytest.fixture
def mp_mesh():
    prev = _state["mesh"]
    mesh = build_mesh(dp=1, mp=8)
    set_mesh(mesh)
    yield mesh
    _state["mesh"] = prev


def _data(seed=0, ignore_every=5):
    rs = np.random.RandomState(seed)
    logits = jnp.asarray(rs.randn(N, V), jnp.float32)
    labels = jnp.asarray(rs.randint(0, V, N), jnp.int32)
    if ignore_every:
        labels = labels.at[::ignore_every].set(-100)
    return logits, labels


def _full_vocab_allgathers(hlo_text):
    """all-gather ops in the HLO whose result shape carries the full
    vocab dim (the partitioner replicating the logits)."""
    hits = []
    for line in hlo_text.splitlines():
        if "all-gather" not in line:
            continue
        shapes = re.findall(r"\[([0-9,]+)\]", line)
        if any(str(V) in s.split(",") for s in shapes):
            hits.append(line.strip())
    return hits


def test_no_full_vocab_all_gather_in_lowered_hlo(mp_mesh):
    logits, labels = _data()
    layer = ParallelCrossEntropy()

    def run(lg, y):
        return layer(Tensor(lg), Tensor(y)).value

    shard = NamedSharding(mp_mesh, P(None, "mp"))
    rep = NamedSharding(mp_mesh, P())
    lg_sh = jax.device_put(logits, shard)
    jitted = jax.jit(run, in_shardings=(shard, rep))
    txt = jitted.lower(lg_sh, labels).compile().as_text()
    gathers = _full_vocab_allgathers(txt)
    assert not gathers, (
        "lowered HLO replicates the full-vocab logits:\n"
        + "\n".join(gathers[:4]))


def test_matches_plain_cross_entropy(mp_mesh):
    logits, labels = _data()
    layer = ParallelCrossEntropy()

    def run(lg, y):
        return layer(Tensor(lg), Tensor(y)).value

    shard = NamedSharding(mp_mesh, P(None, "mp"))
    out = jax.jit(run, in_shardings=(shard, NamedSharding(mp_mesh, P())))(
        jax.device_put(logits, shard), labels)
    ref = F.cross_entropy(Tensor(logits), Tensor(labels),
                          reduction="none").value
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_eager_path_and_custom_ignore_index():
    logits, labels = _data(ignore_every=0)
    labels = labels.at[::4].set(7)
    layer = ParallelCrossEntropy(ignore_index=7)
    out = layer(Tensor(logits), Tensor(labels))
    ref = F.cross_entropy(Tensor(logits), Tensor(labels),
                          reduction="none", ignore_index=7).value
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gather_free_formulation_is_differentiable():
    """one_hot*logits gold must carry the same gradient as the gather
    formulation (softmax(p) - onehot at valid rows, 0 at masked)."""
    logits, labels = _data()

    def mean_loss(lg):
        per_tok = softmax_xent_logits(lg, labels)
        return jnp.sum(per_tok) / jnp.maximum(
            jnp.sum((labels >= 0).astype(jnp.float32)), 1.0)

    def ref_loss(lg):
        logp = jax.nn.log_softmax(lg, axis=-1)
        valid = labels >= 0
        safe = jnp.where(valid, labels, 0)
        picked = jnp.take_along_axis(logp, safe[:, None], axis=-1)[:, 0]
        return jnp.sum(jnp.where(valid, -picked, 0.0)) / jnp.maximum(
            jnp.sum(valid.astype(jnp.float32)), 1.0)

    g = jax.grad(mean_loss)(logits)
    g_ref = jax.grad(ref_loss)(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_tensor_parallel_loss_under_tape():
    """loss.backward() through the layer (eager tape) reaches the
    logits-producing op."""
    logits, labels = _data(ignore_every=0)
    lg = Tensor(logits, stop_gradient=False)
    layer = ParallelCrossEntropy()
    loss = layer(lg, Tensor(labels))
    total = paddle.mean(loss)
    total.backward()
    assert lg.grad is not None
    assert np.isfinite(np.asarray(lg.grad.value)).all()
