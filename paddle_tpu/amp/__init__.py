"""paddle.amp. Parity: python/paddle/amp/ (auto_cast + GradScaler).

TPU-native policy: bf16 is the MXU-native type, needs no loss scaling and
is the default for O1/O2 ('use_bf16'); fp16 paths keep the reference's
dynamic loss scaling semantics in GradScaler. auto_cast works by flipping
a thread-local dtype policy consulted by op dispatch: matmul/conv-class
ops run in the low dtype (white list), numerically-sensitive ops
(softmax/log/reductions — black list) stay fp32, mirroring
paddle/fluid/imperative/amp_auto_cast.cc's lists; norm layers compute
their statistics in f32 internally (nn/functional/norm.py) instead of
being input-cast.
"""
import threading

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor, no_grad
from ..framework.dtype import convert_dtype

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "is_auto_cast_enabled", "get_amp_dtype"]

WHITE_LIST = {"matmul", "conv", "einsum", "bmm", "mm", "linear"}
# norm-family ops are NOT black-listed here: layer_norm/batch_norm compute
# their statistics in f32 internally regardless of amp (nn/functional/
# norm.py) and return the input dtype, which keeps the bf16 activation
# flow intact under O2 — stronger than an input-cast ever is.
BLACK_LIST = {"exp", "log", "softmax", "log_softmax", "cross_entropy",
              "mean", "sum"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def is_auto_cast_enabled():
    return _state.enabled


def get_amp_dtype():
    """Introspection only. NEVER consult this inside a function recorded on
    the eager tape: backward replays outside the autocast context, so any
    dtype decision must be baked at record time via apply_op(op_name=...)
    -> amp_op_dtype."""
    return _state.dtype if _state.enabled else None


# reference kernel names -> our op_name vocabulary, so user code written
# against paddle's custom_white_list/custom_black_list works verbatim
_OP_NAME_ALIASES = {
    "conv2d": "conv", "conv3d": "conv", "conv1d": "conv",
    "conv2d_transpose": "conv", "matmul_v2": "matmul",
    "elementwise_add": "add", "elementwise_sub": "subtract",
    "elementwise_mul": "multiply", "elementwise_div": "divide",
    "softmax_with_cross_entropy": "cross_entropy",
    "reduce_mean": "mean", "reduce_sum": "sum",
}


def _normalize_ops(names):
    return {(_OP_NAME_ALIASES.get(str(n).lower(), str(n).lower()))
            for n in (names or [])}


class auto_cast:
    """Context manager: `with paddle.amp.auto_cast(level='O2'):`

    TPU-native deviation: `dtype` defaults to bfloat16 (the MXU-native
    type, full fp32 range, no loss scaling needed) where the reference
    defaults to float16; pass dtype='float16' for reference semantics."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16"):
        self.enable = enable
        self.level = level
        self.dtype = jnp.bfloat16 if "b" in str(dtype) else jnp.float16
        self.white = _normalize_ops(custom_white_list)
        self.black = _normalize_ops(custom_black_list)

    def __enter__(self):
        self.prev = (_state.enabled, _state.dtype, _state.level,
                     _state.custom_white, _state.custom_black)
        _state.enabled = self.enable
        _state.dtype = self.dtype
        _state.level = self.level
        _state.custom_white = self.white
        _state.custom_black = self.black
        return self

    def __exit__(self, *exc):
        (_state.enabled, _state.dtype, _state.level, _state.custom_white,
         _state.custom_black) = self.prev
        return False


amp_guard = auto_cast


def amp_op_dtype(op_name):
    """Resolve the compute dtype for `op_name` under the active policy, at
    RECORD time. Returns None when no cast applies. The caller (apply_op)
    bakes the result into the taped closure so backward's jax.vjp re-derives
    the exact forward dtypes — the thread-local must never be consulted
    inside a recorded fn (ref: amp_auto_cast.cc casts participate in the
    autograd graph for the same reason)."""
    if not _state.enabled or op_name is None:
        return None
    name = op_name.lower()
    in_white = name in WHITE_LIST or name in _state.custom_white
    in_black = name in BLACK_LIST or name in _state.custom_black
    if _state.level == "O2":
        return jnp.float32 if in_black else _state.dtype
    if in_black:
        return jnp.float32
    return _state.dtype if in_white else None


def amp_cast(x, op_name="matmul"):
    """Cast an input for op `op_name` per the active policy. Delegates to
    amp_op_dtype so the eager tape (apply_op op_name=...) and any direct
    callers resolve the SAME target — one source of truth for the
    white/black-list semantics."""
    target = amp_op_dtype(op_name)
    if target is None:
        return x
    arr = x.value if isinstance(x, Tensor) else x
    if not jnp.issubdtype(arr.dtype, jnp.floating) or arr.dtype == target:
        return x
    return x.astype(target) if isinstance(x, Tensor) else arr.astype(target)


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Parity: paddle.amp.decorate — O2 casts model params to the low
    dtype; optimizers keep fp32 master weights (multi_precision)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m._cast_params(convert_dtype("bfloat16" if "b" in str(dtype)
                                         else "float16"))
    if optimizers is not None:
        single_opt = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if single_opt else list(optimizers)
        for o in opt_list:
            o._multi_precision = True if master_weight is None \
                else bool(master_weight)
        if single_model:
            return models, optimizers
        return model_list, opt_list
    return models if single_model else model_list


class GradScaler:
    """Dynamic loss scaling. Parity: python/paddle/amp/grad_scaler.py.
    bf16 never overflows in practice → scaling becomes identity there,
    but the fp16 semantics (found_inf skip + scale adaptation) are full."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found_dev = jnp.asarray(False)
        with no_grad():
            for p in optimizer._parameters:
                if p.grad is None:
                    continue
                # unscale in f32: 1/scale underflows fp16 normals for large
                # scales, and inf detection must see the pre-cast values
                g32 = p.grad.value.astype(jnp.float32) * inv
                # accumulate the inf check on device; one host sync below
                found_dev = jnp.logical_or(
                    found_dev, jnp.any(~jnp.isfinite(g32)))
                p.grad = Tensor(g32.astype(p.grad.value.dtype))
        self._found_inf = bool(found_dev)
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not getattr(self, "_unscaled", False):
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.unscale_(optimizer)
        self._unscaled = True
        self.step(optimizer)
        self.update()

    def get_loss_scaling(self):
        return self._scale

    # getter/setter surface, parity: grad_scaler.py:78 + loss_scaler.py:40
    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def get_incr_ratio(self):
        return self._incr_ratio

    def set_incr_ratio(self, v):
        if v <= 1.0:
            raise ValueError("incr_ratio must be > 1")
        self._incr_ratio = float(v)

    def get_decr_ratio(self):
        return self._decr_ratio

    def set_decr_ratio(self, v):
        if not 0.0 < v < 1.0:
            raise ValueError("decr_ratio must be in (0, 1)")
        self._decr_ratio = float(v)

    def get_incr_every_n_steps(self):
        return self._incr_every

    def set_incr_every_n_steps(self, v):
        self._incr_every = int(v)

    def get_decr_every_n_nan_or_inf(self):
        return self._decr_every

    def set_decr_every_n_nan_or_inf(self, v):
        self._decr_every = int(v)

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd["scale"]
        self._good_steps = sd["good_steps"]
        self._bad_steps = sd["bad_steps"]

    # -- functional state for the fused jit train step -------------------
    # TrainStep/HybridTrainStep carry this state as device arrays inside
    # the single compiled step (and DONATE it, like params and optimizer
    # state), so dynamic loss scaling costs no host sync per step: the
    # found_inf skip and the scale adaptation are branchless jnp.where
    # selects inside the XLA program.

    def init_jit_state(self):
        """Device-array scaler state for the jitted step. The pytree
        shape is stable across steps (donation-compatible)."""
        return {"scale": jnp.asarray(self._scale, jnp.float32),
                "good_steps": jnp.asarray(self._good_steps, jnp.int32),
                "bad_steps": jnp.asarray(self._bad_steps, jnp.int32)}

    def jit_unscale_and_update(self, state, grads):
        """Pure (call under jit): unscale `grads` by state['scale'],
        detect non-finite gradients, and advance the dynamic-scaling
        state. Returns (unscaled_grads, found_inf, new_state); the
        caller passes found_inf to Optimizer.apply_gradients_tree so an
        overflow step updates nothing (reference: update_loss_scaling
        op + check_finite_and_unscale, fluid/operators/amp/)."""
        import jax
        if not self._enable:
            return grads, jnp.asarray(False), state
        inv = 1.0 / state["scale"]
        leaves = jax.tree.leaves(grads)
        found = jnp.asarray(False)
        for g in leaves:
            found = jnp.logical_or(found, jnp.any(~jnp.isfinite(
                g.astype(jnp.float32))))
        grads = jax.tree.map(
            lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
        return grads, found, self.jit_update_scale_state(state, found)

    def jit_update_scale_state(self, state, found):
        """Pure (call under jit): advance only the dynamic-scaling state
        for a precomputed `found` (traced bool) — the half of
        jit_unscale_and_update the fused multi-tensor epilogue reuses
        (its Pallas pass 1 already produced the unscaled grads and the
        non-finite sweep in one read of the gradients)."""
        if not self._enable or not self._dynamic:
            return state
        incr_every, decr_every = self._incr_every, self._decr_every
        good = jnp.where(found, 0, state["good_steps"] + 1)
        bad = jnp.where(found, state["bad_steps"] + 1, 0)
        incr = good >= incr_every
        decr = bad >= decr_every
        scale = jnp.where(
            decr, jnp.maximum(state["scale"] * self._decr_ratio, 1.0),
            jnp.where(incr, state["scale"] * self._incr_ratio,
                      state["scale"]))
        return {"scale": scale,
                "good_steps": jnp.where(incr, 0, good),
                "bad_steps": jnp.where(decr, 0, bad)}

    def sync_from_jit_state(self, state):
        """Pull the carried device state back into the eager scaler
        (checkpointing via state_dict after jitted training)."""
        self._scale = float(state["scale"])
        self._good_steps = int(state["good_steps"])
        self._bad_steps = int(state["bad_steps"])
