"""Version shims over the jax surface the framework relies on.

The framework targets the current jax API; older jaxlibs (0.4.x) ship
the same functionality under different names. Every cross-version access
goes through here so a version bump is a one-file change.
"""
import functools

__all__ = ["shard_map"]

try:
    from jax import shard_map  # jax >= 0.6
except ImportError:
    # jax 0.4.x: experimental location, and the replication-check kwarg
    # is `check_rep` (renamed to `check_vma` upstream)
    from jax.experimental.shard_map import shard_map as _shard_map_04

    @functools.wraps(_shard_map_04)
    def shard_map(f, /, *, mesh, in_specs, out_specs, check_vma=True,
                  **kwargs):
        return _shard_map_04(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_vma,
                             **kwargs)
