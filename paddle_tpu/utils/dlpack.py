"""paddle.utils.dlpack — zero-copy tensor exchange via the DLPack
protocol.

Parity: /root/reference/python/paddle/utils/dlpack.py. jax arrays speak
DLPack natively, so to_dlpack hands out the capsule of the backing
array and from_dlpack imports straight onto the device.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor → DLPack capsule (no copy; the tensor keeps ownership)."""
    if isinstance(x, Tensor):
        x = x.value
    if not hasattr(x, "__dlpack__"):
        raise TypeError(
            f"to_dlpack expects a paddle Tensor or array, got {type(x)}")
    return x.__dlpack__()


class _CapsuleHolder:
    """Adapter giving a raw capsule the __dlpack__ protocol surface
    jnp.from_dlpack expects.

    A raw capsule carries no producer-device metadata at the Python
    layer, so this path supports HOST-memory producers only: the
    protocol has no way to re-query the real device, and claiming
    kDLCPU for a device buffer would mis-route the import. Producers
    of device memory must pass the exporting object itself (which has
    __dlpack_device__) rather than a bare capsule.
    """

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU — see class docstring


def from_dlpack(dlpack):
    """DLPack capsule (or any object exporting __dlpack__) → Tensor.

    Objects exporting the full protocol (``__dlpack__`` +
    ``__dlpack_device__``) import onto their true device; legacy raw
    capsules are assumed host-resident (see _CapsuleHolder).
    """
    if hasattr(dlpack, "__dlpack__"):
        arr = jnp.from_dlpack(dlpack)
    else:
        arr = jnp.from_dlpack(_CapsuleHolder(dlpack))
    return Tensor(arr)
