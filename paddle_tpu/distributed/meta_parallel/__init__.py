"""Parity: python/paddle/distributed/fleet/meta_parallel/__init__.py."""
from .parallel_layers.mp_layers import (ColumnParallelLinear,
                                        RowParallelLinear,
                                        VocabParallelEmbedding,
                                        ParallelCrossEntropy)
from .parallel_layers.pp_layers import PipelineLayer, LayerDesc, \
    SharedLayerDesc
from .pipeline_parallel import PipelineParallel
