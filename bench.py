"""Headline benchmark: tokens/sec/chip on a GPT train step (bf16).

Prints ONE final JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline ratchets against BENCH_BASE.json (first run records the base;
BASELINE.json carries no published numbers to compare against directly).
On failure, prints a one-line diagnostic JSON instead of a bare traceback.

Robustness contract (round-7; earlier rounds' history in git):
  * compile-wall attack (round-7): the FIRST attempt is scan+names —
    scan-over-layers lowers ONE block body instead of 24, so the cold
    compile is the short one (the unrolled record config runs second,
    on rolled-over budget, once a headline is safe); warmup goes
    through the background warm pipeline (paddle_tpu/jit/warm.py) so
    the headline carries the warm-set wall-vs-sum record; BENCH_CACHE_SEED
    names a donated cache artifact dir (tools/seed_compile_cache.py
    pack) the parent seeds into the compile cache before any attempt —
    a seeded round compiles nothing, and the headline says so
    (cache_seeded / compile_cache_hits); unused seconds from a fast
    (seeded) attempt ROLL OVER to the next attempt instead of the fixed
    per-attempt cap, and the headline records the per-attempt compile
    trajectory (compile_trajectory + compile_history across rounds)
    even for attempts that timed out;
  * a persistent XLA compilation cache (repo-local .xla_cache/ by
    default; BENCH_XLA_CACHE/PADDLE_TPU_COMPILE_CACHE override — the
    same cache the framework itself enables at import, see
    paddle_tpu/framework/compile_cache.py) means any config that has
    EVER compiled on this machine loads in seconds — remote-compile
    congestion can only hurt the first run ever;
  * stdout carries EXACTLY ONE line, the final merged headline JSON (the
    driver contract, tests/test_driver_contract.py); the child's
    measured-instant headline copy and all progress stream to stderr, so
    nothing on stdout can ever be a duplicate or a fragment;
  * the parent fits a total wall budget (BENCH_TOTAL_BUDGET, default
    480 s): attempts are subprocesses with hard timeouts sized to the
    remaining budget — an attempt is NOT launched at all when under 60 s
    of budget remain (the old max(60,...) floor could overrun the
    driver's own kill by ~2 min); the 1.3B side metric runs only after
    the headline result is in hand and only with budget to spare;
  * a compile that exceeds its attempt budget produces a diagnostic JSON
    naming the config, the elapsed time, and the child's last stderr
    lines (congestion evidence) instead of dying silent;
  * BENCH_BASE.json RATCHETS: when a run beats the recorded base, the
    base is rewritten (prior records kept in its `history` list), so
    vs_baseline always measures against the best this machine has done;
  * every attempt carries a PHASE BREAKDOWN (backend_init/import/build/
    compile/steady timings, persistent-cache hit, per-step FLOPs from
    XLA cost analysis, peak memory) in its JSON — success, crash, and
    timeout alike (phases stream over stderr as "bench-phase:" lines,
    so the parent keeps the last one even when it must SIGKILL the
    child). A failed run diagnoses itself; see docs/OBSERVABILITY.md;
  * per-executable compile attribution (round-6): every AOT compile
    streams start/finish over the same bench-phase channel (`compiling`
    cursor + `compiles` table), and the headline carries a
    `compile_ledger` key (tag -> lower_s/compile_s/cache_hit from the
    compilation observatory) — a timed-out round names the executable
    that ate the budget instead of a bare "stage": "compile";
  * the steady phase measures the real async pipeline: batches arrive
    through the device prefetch ring and the loss resolves once at the
    end — `host_blocked_s` in the breakdown separates dispatch-bound
    (~0) from compute-bound (~steady_s) runs (docs/PERFORMANCE.md
    "Hiding the host").
"""
import json
import math
import os
import shutil
import sys
import tempfile
import time
import traceback

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))


def _default_cache_dir():
    """BENCH_XLA_CACHE wins; else the framework-wide
    PADDLE_TPU_COMPILE_CACHE (unless disabled); else repo-local."""
    explicit = os.environ.get("BENCH_XLA_CACHE")
    if explicit:
        return explicit
    fw = os.environ.get("PADDLE_TPU_COMPILE_CACHE", "")
    if fw and fw.strip().lower() not in ("0", "off", "none", "false",
                                         "disabled"):
        return fw
    return os.path.join(_REPO, ".xla_cache")


_CACHE_DIR = _default_cache_dir()
_STATE_PATH = os.path.join(_CACHE_DIR, "bench_state.json")

# Phase breakdown (child-side): updated as each phase completes, so the
# diagnostic JSON of a FAILED attempt still says how far it got and what
# each phase cost — "all attempts failed" with no evidence (BENCH_r05)
# can't happen again. "stage" is the cursor: the phase in flight when
# the record was emitted.
_PHASES = {"stage": "start"}


def _phase(stage, **done):
    _PHASES["stage"] = stage
    for k, v in done.items():
        _PHASES[k] = round(v, 3) if isinstance(v, float) else v
    # stream every transition to stderr: a parent (or the driver log)
    # sees how far a child got even when a hard timeout kills it before
    # it can print any JSON
    print(f"bench-phase: {json.dumps(_PHASES)}", file=sys.stderr,
          flush=True)


def _cache_entries():
    try:
        return sum(1 for n in os.listdir(_CACHE_DIR)
                   if not n.startswith(".") and n != "bench_state.json")
    except OSError:
        return 0


def _enable_compile_cache(jax_mod):
    """Persistent compilation cache: every compile (no minimum time or
    size) is written to the repo-local cache dir, so repeat runs load
    instead of recompiling."""
    try:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        jax_mod.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax_mod.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax_mod.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            # portable cache keys: without this, jax >= 0.4.36 hashes
            # the absolute cache path into every key (via the GPU
            # sub-cache debug options it plants in the dir) and a
            # BENCH_CACHE_SEED-donated artifact can never hit — see
            # framework/compile_cache._make_keys_portable
            jax_mod.config.update(
                "jax_persistent_cache_enable_xla_caches",
                os.environ.get("PADDLE_TPU_CACHE_XLA_CACHES", "none"))
        except Exception:
            pass
        # keep the framework's own cache init (paddle_tpu import below)
        # pointed at the same dir
        os.environ["PADDLE_TPU_COMPILE_CACHE"] = _CACHE_DIR
    except Exception as e:  # cache is an optimization, never a blocker
        print(f"bench: compile cache unavailable: {e}", file=sys.stderr)


def _load_state():
    try:
        with open(_STATE_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


def _save_state(state):
    try:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        with open(_STATE_PATH, "w") as f:
            json.dump(state, f)
    except Exception:
        pass


def _attempt_budget(cap, carry, remaining_s):
    """Rollover budgeting: each attempt gets the fixed per-attempt cap
    PLUS whatever earlier attempts left unused (a cache-seeded first
    attempt finishing in seconds hands its whole window to the next
    config), fenced so the parent always keeps 30 s to merge and
    print."""
    return min(cap + carry, remaining_s - 30)


def _seed_cache():
    """BENCH_CACHE_SEED: pre-populate the bench compile cache from a
    donated artifact dir (a tools/seed_compile_cache.py pack, or any
    raw cache dir) BEFORE any attempt launches, so a machine that has
    never compiled this config loads someone else's compiles instead.
    Pure file copies — the parent stays jax-free (children import the
    framework; the parent only budgets and merges). Returns the seed
    summary dict, or None when the env var is unset."""
    src = os.environ.get("BENCH_CACHE_SEED")
    if not src:
        return None
    info = {"source": src, "entries_seeded": 0, "entries_skipped": 0}
    try:
        if not os.path.isdir(src):
            raise OSError(f"not a directory: {src}")
        os.makedirs(_CACHE_DIR, exist_ok=True)
        for n in sorted(os.listdir(src)):
            if n.startswith(".") or n in ("MANIFEST.json",
                                          "bench_state.json"):
                continue
            sp = os.path.join(src, n)
            if not os.path.isfile(sp):
                continue
            dp = os.path.join(_CACHE_DIR, n)
            if os.path.exists(dp):
                info["entries_skipped"] += 1
                continue
            shutil.copy2(sp, dp)
            info["entries_seeded"] += 1
    except OSError as e:
        # a bad seed degrades to a cold round, never a dead one
        info["error"] = str(e)[:200]
    print(f"bench: cache seed {info}", file=sys.stderr, flush=True)
    return info


def _mark_compiled(tag):
    try:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        state = _load_state()
        state[tag] = {"compiled_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                    time.gmtime())}
        with open(_STATE_PATH, "w") as f:
            json.dump(state, f)
    except Exception:
        pass


def _stream_compiles():
    """Wire the compilation observatory's listener into the bench-phase
    stderr stream: every AOT compile announces itself when it STARTS
    (`compiling: <tag>`) and lands its lower/compile split when it
    finishes, so a child killed at a 300 s timeout still says — in its
    last bench-phase line — WHICH executable ate the budget and which
    ones were already done. Call after paddle_tpu has imported."""
    from paddle_tpu.profiler import compile_observatory as _cobs

    def _on_compile(ev):
        if ev.get("phase") == "start":
            _phase(_PHASES["stage"], compiling=ev.get("tag"))
        else:
            rec = ev.get("record") or {}
            done = list(_PHASES.get("compiles") or [])
            done.append({
                "tag": rec.get("tag"),
                "lower_s": round(float(rec.get("lower_s", 0.0)), 2),
                "compile_s": round(float(rec.get("compile_s", 0.0)), 2),
                "cache_hit": bool(rec.get("cache_hit", False))})
            _phase(_PHASES["stage"], compiling=None, compiles=done[-8:])
    _cobs.add_listener(_on_compile)


def _compile_ledger_table():
    """The headline's per-executable compile table: tag -> lower_s /
    compile_s / cache_hit (+ signature count and fusion count), rolled
    up from the compilation observatory's ledger."""
    try:
        from paddle_tpu.profiler import compile_observatory as _cobs
        return {tag: {"lower_s": round(a["lower_s"], 3),
                      "compile_s": round(a["compile_s"], 3),
                      "cache_hit": a["cache_hit"],
                      "signatures": a["signatures"],
                      "fusion_count": a["fusion_count"]}
                for tag, a in sorted(_cobs.aggregate().items())}
    except Exception:
        return {}


def _timed_checkpoint(step_obj):
    """One timed save of the bench model through the production
    checkpoint path: returns {"ckpt_snapshot_s", "ckpt_write_s",
    "ckpt_bytes", "ckpt_total_s"} from the save's kind:"ckpt" record,
    or {} when checkpointing failed (never costs the bench record).
    The checkpoint lands in a throwaway temp dir and is deleted."""
    import shutil
    d = None
    try:
        from paddle_tpu.distributed.checkpoint import CheckpointManager
        d = tempfile.mkdtemp(prefix="bench_ckpt_")
        mgr = CheckpointManager(d, keep_last=1)
        handle = mgr.save(step_obj)
        handle.result(300)
        rec = handle.record
        mgr.close()
        return {"ckpt_snapshot_s": round(float(rec["snapshot_s"]), 4),
                "ckpt_write_s": round(float(rec["write_s"]), 4),
                "ckpt_bytes": int(rec["bytes"]),
                "ckpt_total_s": round(float(rec["total_s"]), 4)}
    except Exception as e:
        print(f"bench: timed checkpoint unavailable: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
        return {}
    finally:
        if d:
            shutil.rmtree(d, ignore_errors=True)


def _peak_flops(jax_mod):
    """bf16 peak for the attached chip generation (MFU denominator) —
    the framework's single table (paddle_tpu/profiler/cost.py), with
    bench's traditional 197e12 fallback for unknown chips."""
    try:
        from paddle_tpu.profiler.cost import device_peak_flops
        return device_peak_flops(jax_mod.devices()[0], default=197e12)
    except Exception:
        return 197e12


def _run():
    import signal

    init_budget = int(os.environ.get("BENCH_INIT_TIMEOUT", "240"))

    def _init_timeout(signum, frame):
        raise TimeoutError(
            f"TPU backend init did not complete within {init_budget}s — "
            "axon tunnel unreachable (jax.devices() blocked on recvfrom)")

    # backend init goes through the axon tunnel; if the tunnel is wedged
    # the first device query blocks forever — fail with a diagnostic
    # instead (observed 2026-07-29: tunnel outage mid-round)
    signal.signal(signal.SIGALRM, _init_timeout)
    signal.alarm(init_budget)
    _phase("backend_init")
    t_phase = time.perf_counter()
    import jax
    import jax.numpy as jnp
    _enable_compile_cache(jax)
    jax.devices()  # force backend init under the alarm
    signal.alarm(0)
    _phase("import", backend_init_s=time.perf_counter() - t_phase)

    t_phase = time.perf_counter()
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
    _stream_compiles()  # per-executable compile progress -> bench-phase
    _phase("build", import_s=time.perf_counter() - t_phase)
    t_phase = time.perf_counter()

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # Compile-bound default (round-7): scan_layers=True + "names"
        # remat — XLA lowers ONE block body instead of 24, so the cold
        # compile is minutes shorter; this is what finally gets a
        # headline past the 300 s compile wall (five rounds of timeouts
        # with the old unrolled-first order). The unrolled config
        # (scan=0, remat=false) stays the runtime record holder —
        # 193 ms/step vs 249 ms measured in r3 — but its cold compile
        # is the longest, so the parent runs it SECOND, on rolled-over
        # budget, once a scan headline is already in hand (seconds from
        # the persistent cache once it has ever compiled).
        batch, seq = 8, 1024
        remat = os.environ.get("BENCH_REMAT", "names")
        if remat not in ("true", "false", "names", "dots"):
            raise ValueError(f"BENCH_REMAT={remat!r}: expected "
                             "true|false|names|dots")
        scan = os.environ.get("BENCH_SCAN", "1") == "1"
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                        num_heads=16, max_position_embeddings=seq,
                        dropout=0.0, scan_layers=scan,
                        scan_remat={"true": True,
                                    "false": False}.get(remat, remat))
    else:  # smoke-size on CPU so the script always runs
        batch, seq = 2, 128
        remat = scan = None  # report keys: config not applied off-TPU
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=seq,
                        dropout=0.0)

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.bfloat16() if on_tpu else None
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())

    # multi_precision: f32 master weights — a bf16 param's ulp (~2^-8
    # relative) would otherwise swallow typical late-training updates
    o = opt.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                  multi_precision=on_tpu)

    def loss_fn(logits, labels):
        V = logits.shape[-1]
        return nn.functional.cross_entropy(
            logits.reshape([-1, V]), labels.reshape([-1]))

    # monitor_health: the in-graph health vector (grad norm / update
    # ratio) rides the compiled step on the async path — the headline
    # carries the final values, and an anomalous run says so itself
    step = TrainStep(model, loss_fn, o, monitor_health=True)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32))
    cache_entries_before = _cache_entries()
    _phase("compile", build_s=time.perf_counter() - t_phase,
           cache_warm=cache_entries_before > 0)

    # warmup through the BACKGROUND warm pipeline (jit/warm.py): the
    # compile runs on a worker thread with the exact steady-state
    # signature (same _prep, same donation — warming adds zero
    # executables), jit.warm.join records the warm-set wall-vs-sum
    # evidence, and the first real step below joins the already-warm
    # executable. Sync via a data fetch — through the axon tunnel
    # block_until_ready returns before execution finishes, so only a
    # fetch (.item()) is a true barrier
    from paddle_tpu.jit import warm as jwarm
    t_compile = time.perf_counter()
    warm_summary = jwarm.join([step.warm(ids, ids)])
    for _ in range(3):
        loss = step(ids, ids)
    float(loss.item())
    t_compile = time.perf_counter() - t_compile
    _mark_compiled(f"headline scan={scan} remat={remat}")
    # the AOT executable cache knows whether the compile loaded from the
    # persistent cache and what the per-step FLOPs are (free — no
    # re-lower); see paddle_tpu/jit/api.py aot_compile
    exec_info = next(iter(step._exec.values()))[1] if step._exec else {}
    flops_per_step = float(exec_info.get("flops", 0.0))
    _phase("steady", compile_warmup_s=t_compile,
           compile_cache_hit=bool(exec_info.get("cache_hit", False)),
           compile_lower_s=float(exec_info.get("lower_s", 0.0)),
           compile_xla_s=float(exec_info.get("compile_s", 0.0)))
    print(f"bench: warmup+compile {t_compile:.1f}s "
          f"(scan={scan} remat={remat})", file=sys.stderr, flush=True)

    # steady phase runs the real pipeline: batches flow through the
    # device prefetch ring (H2D staged ahead by a background thread) and
    # the deferred loss is resolved ONCE at the end — host_blocked_s is
    # the steady-phase host wait, so the headline says whether this
    # config is dispatch-bound (~0) or compute-bound (~steady_s)
    from paddle_tpu.io.device_prefetch import device_prefetch_iterator
    from paddle_tpu.profiler import monitor as _pmon
    iters = 30 if on_tpu else 3
    blocked_before = _pmon.host_blocked_s()
    t0 = time.perf_counter()
    loss = None
    for b_ids, b_labels in device_prefetch_iterator(
            ((ids, ids) for _ in range(iters)), depth=2,
            sharding_fn=step.input_sharding):
        loss = step(b_ids, b_labels)
    float(loss.item())
    dt = time.perf_counter() - t0
    host_blocked = _pmon.host_blocked_s() - blocked_before
    _phase("done", steady_s=dt, steady_iters=iters,
           host_blocked_s=host_blocked,
           peak_bytes=int(paddle.device.max_memory_allocated()),
           flops_per_step=flops_per_step,
           cache_entries=_cache_entries())

    tokens_per_sec = batch * seq * iters / dt
    loss_val = round(float(loss.item()), 4)

    # measured device time (the distributed observatory's sampled
    # probe, PADDLE_TPU_DEVICE_TIME_EVERY — default cadence 16 fires
    # inside the 30-iter steady loop): median measured step time,
    # cost-analysis-FLOPs-over-MEASURED-time MFU, and the
    # collective-overlap fraction — the headline's measured companion
    # to the two analytic MFU numbers below
    from paddle_tpu.profiler import dist_observatory as _pdobs
    device_probe = _pdobs.device_time_summary()

    # memory-observatory report while the train step (params/opt_state
    # tags) is still alive — the headline's measured memory baseline
    from paddle_tpu.profiler import mem_observatory as _mobs
    _mem_rep = _mobs.mem_report()

    # training-health tail + unified Perfetto trace (ring snapshot —
    # milliseconds; both before the headline print so they ride in it)
    health = step.flush_health() or {}
    anomalies = step.anomalies.drain() if step.anomalies else []
    try:
        from paddle_tpu.profiler import trace_export
        trace_file = trace_export.write_chrome_trace(os.path.join(
            tempfile.gettempdir(), "paddle_tpu_bench_trace.json"))
    except Exception as e:  # telemetry never costs the record
        trace_file = f"unavailable: {type(e).__name__}"

    # ---- the headline is now measured: print it IMMEDIATELY (the parent
    # tees this line straight through, so any later kill cannot lose it)
    peak = _peak_flops(jax) if on_tpu else 197e12
    mfu = 6.0 * n_params * tokens_per_sec / peak if on_tpu else 0.0
    base_path = os.path.join(_REPO, "BENCH_BASE.json")
    vs = 1.0
    if on_tpu:
        if os.path.exists(base_path):
            with open(base_path) as f:
                base_rec = json.load(f)
            base = base_rec.get("tokens_per_sec", tokens_per_sec)
            vs = tokens_per_sec / base
            if tokens_per_sec > base:
                # ratchet: this run is the new base; keep prior records
                # so the trail of bests is auditable
                hist = base_rec.pop("history", [])
                hist.append(base_rec)
                with open(base_path, "w") as f:
                    json.dump({"tokens_per_sec": tokens_per_sec,
                               "mfu": mfu, "n_params": n_params,
                               "recorded_utc": time.strftime(
                                   "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                               "history": hist[-20:]}, f)
        else:
            with open(base_path, "w") as f:
                json.dump({"tokens_per_sec": tokens_per_sec,
                           "mfu": mfu, "n_params": n_params}, f)
    headline = {
        "metric": "gpt_medium_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 3),
        "on_tpu": on_tpu,
        "mfu": round(mfu, 4),
        "remat": remat,
        "scan_layers": scan,
        "loss": loss_val,
        "compile_s": round(t_compile, 1),
        # perf provenance: warm-start + in-place-update evidence
        "compile_cache_warm": cache_entries_before > 0,
        "compile_cache_entries": _cache_entries(),
        # entries-hit: how many executables loaded from the persistent
        # cache (a seeded round reports all of them here) + the warm
        # pipeline's wall-vs-sum record for this attempt's warm set
        "compile_cache_hits": sum(
            1 for a in _compile_ledger_table().values()
            if a.get("cache_hit")),
        "warm_wall_s": warm_summary["wall_s"],
        "warm_sum_s": warm_summary["sum_s"],
        "retraces": step.retraces,
        "donated": step._donate,
        "peak_mem_bytes": int(paddle.device.max_memory_allocated()),
        # memory-observatory peak (profiler/mem_observatory): the
        # device-wide high-water mark, bounded below by the tagged
        # ledger so CPU hosts (memory_stats() == {}) still report the
        # attributed footprint instead of 0
        "hbm_peak_bytes": int(_mem_rep["device_peak_bytes"]),
        "mem_attributed_bytes": int(_mem_rep["attributed_bytes"]),
        # XLA cost analysis (per-executable FLOPs) — the measured-work
        # MFU companion to the 6ND estimate above
        "flops_per_step": flops_per_step,
        "mfu_cost_analysis": round(
            flops_per_step * iters / dt / peak, 4) if on_tpu else 0.0,
        # measured device time (dist_observatory sampled probe): the
        # first MFU in this repo derived from MEASURED device seconds
        # instead of XLA cost analysis or 6ND; overlap_fraction is the
        # share of the measured window not spent in host-visible
        # collective waits. 0/absent-sample values when the probe never
        # fired (PADDLE_TPU_DEVICE_TIME_EVERY=0).
        "step_time_device_s": round(
            device_probe.get("step_time_device_s", 0.0), 6),
        "mfu_measured": round(device_probe.get("mfu_measured", 0.0), 4),
        "overlap_fraction": round(
            device_probe.get("overlap_fraction", 0.0), 4),
        "device_probe_samples": int(device_probe.get("samples", 0)),
        # fused multi-tensor update epilogue (ops/pallas/
        # fused_update.py): analytic HBM bytes of the two update passes
        # and their share of the executable's cost-analysis bytes — the
        # step-cost slice the epilogue is responsible for. 0/0.0 when
        # the tree path is active (PADDLE_TPU_FUSED_UPDATE=0 or an
        # unsupported optimizer/clip config).
        "epilogue_bytes_per_step": int(
            getattr(step, "_epilogue_bytes", 0) or 0),
        "epilogue_share": round(min(
            (getattr(step, "_epilogue_bytes", 0) or 0)
            / max(float(exec_info.get("bytes", 0.0)), 1.0), 1.0), 4),
        # in-graph health observatory (monitor_health=True): final grad
        # norm / update ratio, plus how many anomaly events the host
        # detectors emitted over the run (0 = numerically clean)
        "health": {k: (round(v, 6) if isinstance(v, float)
                       and math.isfinite(v) else repr(v))
                   for k, v in health.items()
                   if k in ("grad_norm", "update_ratio", "found_inf")},
        "anomaly_events": len(anomalies),
        # unified Chrome-trace export (open in Perfetto; merge per-rank
        # files with tools/merge_traces.py)
        "trace_file": trace_file,
        # the compilation observatory's per-executable ledger: where the
        # compile seconds went, per tag, with cache-hit attribution —
        # the compile-time wall (ROADMAP item 3) finally itemized
        "compile_ledger": _compile_ledger_table(),
        "phases": dict(_PHASES),
    }
    print(json.dumps(headline), flush=True)

    # persist the measured-device-time trajectory across rounds
    # (bench_state.json, like ckpt_history) so a probe regression —
    # measured time drifting away from the throughput-implied time, or
    # overlap collapsing — shows up in the history, not just one round
    if device_probe:
        state = _load_state()
        hist = state.get("device_time_history", [])
        hist.append({
            "step_time_device_s": device_probe["step_time_device_s"],
            "mfu_measured": device_probe["mfu_measured"],
            "overlap_fraction": device_probe["overlap_fraction"],
            "samples": device_probe["samples"],
            "tokens_per_sec": round(tokens_per_sec, 1),
            "on_tpu": on_tpu,
            "recorded_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())})
        state["device_time_history"] = hist[-10:]
        _save_state(state)

    if os.environ.get("BENCH_HOLD_AFTER_PRINT"):
        # test hook: prove the headline survives a kill after measurement
        time.sleep(float(os.environ["BENCH_HOLD_AFTER_PRINT"]))

    # ---- checkpoint latency side metric (AFTER the headline line so a
    # slow disk can never cost the throughput record): ONE timed
    # snapshot-then-write save of the bench model through the real
    # fault-tolerance path (distributed/checkpoint.py), phases from its
    # kind:"ckpt" record, persisted into bench_state.json so
    # checkpoint-latency regressions show up in the trajectory
    ck = _timed_checkpoint(step)
    if ck:
        headline.update(ck)
        state = _load_state()
        hist = state.get("ckpt_history", [])
        hist.append(dict(ck, recorded_utc=time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()), on_tpu=on_tpu,
            n_params=n_params))
        state["ckpt_history"] = hist[-10:]
        _save_state(state)
        print(json.dumps(headline), flush=True)

    # calibrate sustained matmul rate (the realistic MXU ceiling for this
    # chip/tunnel) with a 100-iter chained bf16 matmul, one scalar fetch.
    # Runs AFTER the headline line so it can never cost the record.
    mm_tflops = 0.0
    if on_tpu and os.environ.get("BENCH_MM_CAL", "1") == "1":
        from jax import lax
        a = jnp.asarray(rng.randn(4096, 4096) * 0.01, jnp.bfloat16)
        w = jnp.asarray(rng.randn(4096, 4096) * 0.01, jnp.bfloat16)

        @jax.jit
        def mm_chain(x):
            def body(c, _):
                return (c @ w) * 0.01, None
            y, _ = lax.scan(body, x, None, length=100)
            return y.ravel()[0].astype(jnp.float32)

        float(mm_chain(a))
        t0 = time.perf_counter()
        float(mm_chain(a))
        mm_dt = time.perf_counter() - t0
        mm_tflops = 100 * 2 * 4096**3 / mm_dt / 1e12
        # mfu uses the chip-generation nominal peak; mfu_vs_measured_peak
        # uses the sustained bf16 matmul rate calibrated above (~100
        # TFLOP/s on this chip/tunnel) — the honest utilization ceiling
        headline["measured_matmul_tflops"] = round(mm_tflops, 1)
        headline["mfu_vs_measured_peak"] = round(
            6.0 * n_params * tokens_per_sec / (mm_tflops * 1e12), 4)
        print(json.dumps(headline), flush=True)


def _run_1p3b():
    """Child task (BENCH_TASK=1p3b): flagship-scale side metric (VERDICT
    r3 #4) — GPT-1.3B on this one chip, bf16 velocity + stochastic
    rounding (master-weight-grade precision without the f32 copies;
    tests/test_stochastic_rounding.py). Round-4 sweep winner: scan +
    SELECTIVE remat ("dots": save matmul outputs, recompute elementwise)
    + the chunked vocab xent (fused_loss) — the chunked xent frees the
    [B*T, V] logits, which is exactly what lets the "dots" policy fit
    on the 16 GB chip (full remat: 11.0k tok/s; this config: 11.9k,
    +7.5%). Runs in its OWN subprocess so a congested compile can never
    starve the headline metric (the parent already holds that line)."""
    _phase("backend_init")
    import jax
    import jax.numpy as jnp
    _enable_compile_cache(jax)
    _phase("import")
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_1p3b
    from paddle_tpu.optimizer import Momentum
    _stream_compiles()  # per-executable compile progress -> bench-phase
    _phase("build")

    cfg13 = gpt_1p3b()
    cfg13.max_position_embeddings = 1024
    cfg13.dropout = 0.0
    cfg13.scan_layers = True
    cfg13.scan_remat = os.environ.get("BENCH_1P3B_REMAT", "dots")
    if cfg13.scan_remat in ("true", "false"):
        cfg13.scan_remat = cfg13.scan_remat == "true"
    paddle.seed(0)
    m13 = GPTForCausalLM(cfg13)
    m13.bfloat16()
    o13 = Momentum(learning_rate=1e-4, momentum=0.9,
                   parameters=m13.parameters())
    o13._stochastic_rounding = True
    o13._state_dtype = jnp.bfloat16
    n13 = sum(int(np.prod(p.shape)) for p in m13.parameters())

    class _FusedLossWrapper(nn.Layer):
        def __init__(self, lm):
            super().__init__()
            self.lm = lm

        def forward(self, ids, labels):
            return self.lm.fused_loss(ids, labels, chunk=2048)

    s13 = TrainStep(_FusedLossWrapper(m13), None, o13,
                    model_returns_loss=True)
    rng = np.random.RandomState(0)
    ids13 = paddle.to_tensor(rng.randint(
        0, cfg13.vocab_size, size=(4, 1024)).astype(np.int32))
    _phase("compile")
    t_c = time.perf_counter()
    for _ in range(2):
        l13 = s13(ids13, ids13)
    float(l13.item())
    _mark_compiled(f"1p3b remat={cfg13.scan_remat}")
    _phase("steady", compile_warmup_s=time.perf_counter() - t_c)
    t0 = time.perf_counter()
    for _ in range(8):
        l13 = s13(ids13, ids13)
    float(l13.item())
    tps = 4 * 1024 * 8 / (time.perf_counter() - t0)
    peak = _peak_flops(jax)
    print(json.dumps({"gpt_1p3b_tokens_per_sec": round(tps, 1),
                      "gpt_1p3b_mfu": round(6.0 * n13 * tps / peak, 4)}),
          flush=True)


def _serve_gen_workload():
    """The mixed long/short-prompt GENERATION workload behind
    `bench.py --serve` (docs/SERVING.md "Ragged serving"): the same
    prompt set — short chats and long documents behind one shared
    system prefix — runs through the BUCKETED GenerationEngine
    (ragged=False: fixed-shape decode, pad rows pay full attention)
    and then the RAGGED engine (Pallas mixed prefill+decode kernel,
    chunked prefill, refcounted prefix caching). Returns the headline
    dict: per-path pad-token fraction (same counter-delta formula for
    both), prefix hit rate, client-side TTFT p50/p99, and the
    token-for-token equality verdict."""
    import threading
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
    from paddle_tpu.inference import GenerationEngine
    from paddle_tpu.profiler import monitor as _pmon
    from paddle_tpu.profiler import serve_observatory as _sobs
    from paddle_tpu.profiler import mem_observatory as _mobs

    n_long = int(os.environ.get("BENCH_SERVE_GEN_LONG", "2"))
    n_short = int(os.environ.get("BENCH_SERVE_GEN_SHORT", "6"))
    max_new = int(os.environ.get("BENCH_SERVE_GEN_NEW", "6"))
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    system = rng.randint(0, 256, (16,))  # the shared system prompt
    # long documents generate 3x the tokens of short chats: finish
    # times stagger, so the bucketed path's decode batch regularly
    # sits between power-of-two buckets — the pad rows whose full-
    # width attention cost the ragged kernel skips
    prompts = [np.concatenate([system, rng.randint(0, 256, (n,))])
               for n in [40] * n_long + [4] * n_short]
    new_toks = [3 * max_new] * n_long + \
        [max_new + i % 3 for i in range(n_short)]
    total_prompt_toks = sum(p.size for p in prompts)

    def run(ragged):
        c0 = {k: _pmon.get_metric(f"serve.{k}")
              for k in ("pad_tokens", "prefix_hits",
                        "chunked_prefill_tokens", "goodput_tokens",
                        "wasted_tokens")}
        base = {k: (int(m.value) if m else 0) for k, m in c0.items()}
        slo0 = _sobs.slo_report()["deadline"]
        eng = GenerationEngine(model, n_pages=128, page_size=8,
                               max_batch=4, max_new_tokens=max_new,
                               ragged=ragged, prefill_chunk=16,
                               name=f"bench_{'ragged' if ragged else 'bucketed'}")
        # OVERLAPPED warm before the timed region (the PR 7 pipeline):
        # every ragged (T, B, W) signature this prompt set can dispatch
        # compiles through the background warm executor, streaming
        # per-executable progress to bench-phase — on axon, cold
        # compiles inside the timed loop were the r04/r05 round-killer
        # (the bucketed path has no warm schedule; it compiles its two
        # decode buckets inline as it always did)
        if ragged:
            from paddle_tpu.jit import warm as jwarm
            jwarm.join([h for p, n in zip(prompts, new_toks)
                        for h in eng.warm_async(p.size, n)])
        outs, ttfts = [None] * len(prompts), [None] * len(prompts)
        t0 = time.perf_counter()
        # a generous per-request SLO: attainment < 1.0 on this tiny
        # workload means the engine (or the host) is badly degraded —
        # exactly the regression serve_history exists to surface
        handles = [eng.submit(p, max_new_tokens=n, deadline_ms=120_000)
                   for p, n in zip(prompts, new_toks)]

        def drain(i, h):
            toks = []
            for tok in h.tokens():
                if not toks:
                    ttfts[i] = time.perf_counter() - t0
                toks.append(tok)
            outs[i] = toks

        threads = [threading.Thread(target=drain, args=(i, h))
                   for i, h in enumerate(handles)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        frac = eng.pad_token_fraction()
        kv_peak = eng.kv_peak_occupancy()
        # measured memory gauges BEFORE shutdown frees the pool: the
        # pool's resident bytes, its free-list fragmentation, and the
        # device peak — the baseline the next capacity PR has to beat
        hbm = _mobs.pool_hbm(eng.cache)
        frag_kv = _mobs.fragmentation(eng.cache)
        mem_rep = _mobs.mem_report()
        eng.shutdown()
        delta = {k: (int(m2.value) if (m2 := _pmon.get_metric(
            f"serve.{k}")) else 0) - v for k, v in base.items()}
        slo1 = _sobs.slo_report()["deadline"]
        slo_total = slo1["requests"] - slo0["requests"]
        slo_met = slo1["met"] - slo0["met"]
        goodput = delta["goodput_tokens"]
        wasted = delta["wasted_tokens"]
        ttfts_ms = sorted(1e3 * t for t in ttfts if t is not None)
        return {
            "outs": outs, "wall_s": round(wall, 3),
            "gen_tokens_per_sec": round(
                sum(len(o or []) for o in outs) / wall, 1),
            # MEASURED attention-slot waste (engine accounting, same
            # formula both paths): slots computed outside any causal
            # bound / slots computed — bucketed decode pays pad rows +
            # the pow2 table width, the ragged kernel only intra-page
            # remainders
            "pad_token_fraction": round(frac, 4),
            "pad_row_tokens": delta["pad_tokens"],
            "prefix_hit_rate": round(
                delta["prefix_hits"] / max(total_prompt_toks, 1), 4),
            "chunked_prefill_tokens": delta["chunked_prefill_tokens"],
            # SLO/goodput accounting (profiler/serve_observatory):
            # deadline attainment over this run's deadline-carrying
            # requests, useful-vs-dead generated tokens, and the page
            # pool's peak occupancy (pad page excluded)
            "slo_attainment": round(slo_met / slo_total, 4)
            if slo_total else 1.0,
            "goodput_tokens_per_s": round(goodput / wall, 1),
            "wasted_token_fraction": round(
                wasted / max(goodput + wasted, 1), 4),
            "kv_peak_occupancy": round(kv_peak, 4),
            # memory observatory gauges (profiler/mem_observatory):
            # pool footprint, free-list fragmentation at run end, and
            # the device-wide peak (ledger-attributed on CPU hosts)
            "kv_pool_bytes": int(hbm.get("hbm_total_bytes", 0)),
            "fragmentation": round(frag_kv["fragmentation"], 4)
            if frag_kv is not None else 0.0,
            "hbm_peak_bytes": int(mem_rep["device_peak_bytes"]),
            "ttft_p50_ms": round(
                ttfts_ms[len(ttfts_ms) // 2], 1) if ttfts_ms else 0.0,
            "ttft_p99_ms": round(
                ttfts_ms[min(len(ttfts_ms) - 1,
                             int(0.99 * len(ttfts_ms)))], 1)
            if ttfts_ms else 0.0,
        }

    bucketed = run(ragged=False)
    ragged = run(ragged=True)
    equal = bucketed.pop("outs") == ragged.pop("outs")
    return {
        "prompts": {"long": n_long, "short": n_short,
                    "shared_prefix": int(system.size),
                    "max_new_tokens": max_new},
        "ragged": ragged, "bucketed": bucketed,
        "ragged_equals_bucketed": equal,
        # the acceptance comparison, measured in the same run
        "pad_token_fraction_ragged": ragged["pad_token_fraction"],
        "pad_token_fraction_bucketed": bucketed["pad_token_fraction"],
        "prefix_hit_rate": ragged["prefix_hit_rate"],
        "ttft_p50_ms": ragged["ttft_p50_ms"],
        "ttft_p99_ms": ragged["ttft_p99_ms"],
        # the serving-observatory headline (ragged path — the default)
        "slo_attainment": ragged["slo_attainment"],
        "goodput_tokens_per_s": ragged["goodput_tokens_per_s"],
        "wasted_token_fraction": ragged["wasted_token_fraction"],
        "kv_peak_occupancy": ragged["kv_peak_occupancy"],
        "kv_pool_bytes": ragged["kv_pool_bytes"],
        "fragmentation": ragged["fragmentation"],
        "hbm_peak_bytes": ragged["hbm_peak_bytes"],
    }


def _serve_router_workload():
    """The FRONT-DOOR topology comparison behind `bench.py --serve`
    (docs/SERVING.md "The front door"): the same mixed long/short
    prompt set runs through (a) ONE GenerationEngine with 4 decode
    slots and (b) a disaggregated 2-engine ServingRouter — a
    prefill-role engine (2 slots) handing KV chains to a decode-role
    engine (2 slots) over the SAME-SIZED shared page pool. Equal total
    chips/slots, so `router_speedup_vs_single` is a scheduling win,
    not a capacity one. Reports req/s, client-side TTFT p50/p99, fleet
    SLO attainment, the handoff count, and token-for-token equality
    (both paths decode greedily)."""
    import threading
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
    from paddle_tpu.inference import GenerationEngine, ServingRouter
    from paddle_tpu.profiler import monitor as _pmon
    from paddle_tpu.profiler import serve_observatory as _sobs

    n_reqs = int(os.environ.get("BENCH_SERVE_ROUTER_REQS", "8"))
    max_new = int(os.environ.get("BENCH_SERVE_GEN_NEW", "6"))
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(1)
    system = rng.randint(0, 256, (16,))  # shared system prompt
    # every 4th request is a long document; the rest are short chats —
    # the regime where decoupling prefill from the decode cadence pays
    lens = [40 if i % 4 == 0 else 4 for i in range(n_reqs)]
    prompts = [np.concatenate([system, rng.randint(0, 256, (n,))])
               for n in lens]

    def run(submit, shutdown):
        slo0 = _sobs.slo_report()["deadline"]
        outs, ttfts = [None] * len(prompts), [None] * len(prompts)
        t0 = time.perf_counter()
        handles = [submit(p) for p in prompts]

        def drain(i, h):
            toks = []
            for tok in h.tokens():
                if not toks:
                    ttfts[i] = time.perf_counter() - t0
                toks.append(tok)
            outs[i] = toks

        threads = [threading.Thread(target=drain, args=(i, h))
                   for i, h in enumerate(handles)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        shutdown()
        slo1 = _sobs.slo_report()["deadline"]
        slo_total = slo1["requests"] - slo0["requests"]
        slo_met = slo1["met"] - slo0["met"]
        ttfts_ms = sorted(1e3 * t for t in ttfts if t is not None)
        return {
            "outs": outs, "wall_s": round(wall, 3),
            "req_per_sec": round(len(prompts) / wall, 2),
            "gen_tokens_per_sec": round(
                sum(len(o or []) for o in outs) / wall, 1),
            "slo_attainment": round(slo_met / slo_total, 4)
            if slo_total else 1.0,
            "ttft_p50_ms": round(
                ttfts_ms[len(ttfts_ms) // 2], 1) if ttfts_ms else 0.0,
            "ttft_p99_ms": round(
                ttfts_ms[min(len(ttfts_ms) - 1,
                             int(0.99 * len(ttfts_ms)))], 1)
            if ttfts_ms else 0.0,
        }

    # untimed warm pass BEFORE either timed topology — the model's
    # executable cache is per-process, so without this whichever
    # topology ran first would pay the compiles the other one reuses.
    # Two stages: the OVERLAPPED warm pipeline compiles every (T, B, W)
    # signature the prompt set can dispatch (background executor,
    # per-executable progress on bench-phase), then one short-decode
    # execution pass covers first-run effects and any admission-order
    # signature the simulated schedule missed
    from paddle_tpu.jit import warm as jwarm
    warm_eng = GenerationEngine(model, n_pages=128, page_size=8,
                                max_batch=4, max_new_tokens=2,
                                prefill_chunk=16, name="bench_warmup")
    jwarm.join([h for p in prompts
                for h in warm_eng.warm_async(p.size, max_new)])
    for h in [warm_eng.submit(p, max_new_tokens=2) for p in prompts]:
        h.result(300)
    warm_eng.shutdown()

    # (a) single engine: 4 decode slots over one 128-page pool
    eng = GenerationEngine(model, n_pages=128, page_size=8,
                           max_batch=4, max_new_tokens=max_new,
                           prefill_chunk=16, name="bench_single")
    single = run(lambda p: eng.submit(p, max_new_tokens=max_new,
                                      deadline_ms=120_000),
                 eng.shutdown)
    # (b) disaggregated router: prefill 2 + decode 2 slots, SAME pool
    # size — equal chips. Signatures reuse (a)'s persistent-cache
    # entries (same model config, same pool geometry).
    h0 = _pmon.get_metric("serve.route_handoffs")
    h0 = int(h0.value) if h0 else 0
    router = ServingRouter.disaggregated(
        model, n_pages=128, page_size=8, max_batch=2, prefill_batch=2,
        max_new_tokens=max_new, prefill_chunk=16, name="bench_router")
    routed = run(lambda p: router.submit(p, max_new_tokens=max_new,
                                         deadline_ms=120_000),
                 lambda: router.shutdown())
    h1 = _pmon.get_metric("serve.route_handoffs")
    handoffs = (int(h1.value) if h1 else 0) - h0
    equal = single.pop("outs") == routed.pop("outs")
    return {
        "requests": n_reqs,
        "topology": {"single": "1 engine x 4 slots, 128-page pool",
                     "router": "prefill 2 + decode 2 slots, shared "
                               "128-page pool"},
        "single": single, "router": routed,
        "router_equals_single": equal,
        "handoff_count": handoffs,
        "router_speedup_vs_single": round(
            single["wall_s"] / routed["wall_s"], 3)
        if routed["wall_s"] else 0.0,
        "router_slo_attainment": routed["slo_attainment"],
        "router_ttft_p50_ms": routed["ttft_p50_ms"],
        "router_ttft_p99_ms": routed["ttft_p99_ms"],
    }


def _serve_load_workload():
    """The OPEN-LOOP load stage behind `bench.py --serve`
    (tools/load_harness.py, docs/OBSERVABILITY.md "The fleet
    observatory"): a seeded deterministic trace — Poisson arrivals
    with a 10x burst window, heavy-tailed lengths, tiered SLO mix —
    drives a 2-engine disaggregated router open-loop (arrivals never
    wait on completions, so the burst actually overloads admission).
    Returns the harness summary: goodput tokens/s, per-class SLO
    attainment, TTFT/TPOT percentiles, rejected/expired fractions,
    peak in-flight, and the pressure-event count."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingRouter
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import load_harness as _lh

    seed = int(os.environ.get("BENCH_SERVE_LOAD_SEED", "0"))
    n_reqs = int(os.environ.get("BENCH_SERVE_LOAD_REQS", "16"))
    rate = float(os.environ.get("BENCH_SERVE_LOAD_RATE", "4"))
    max_new = int(os.environ.get("BENCH_SERVE_GEN_NEW", "6"))
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    burst = (0.4, 0.7, 10.0)
    trace = _lh.generate_trace(seed, n_reqs, rate_rps=rate,
                               burst=burst,
                               max_prompt=48, max_out=max_new,
                               vocab=256)
    # small admission queue on purpose: the 10x burst must actually
    # reject at the front door, or the open-loop stage measures nothing
    # the closed-loop stages don't
    router = ServingRouter.disaggregated(
        model, n_pages=128, page_size=8, max_batch=2, max_queue=4,
        max_new_tokens=max_new, prefill_chunk=16, name="bench_load",
        fleet_snapshot_s=0.5)
    try:
        summary = _lh.run_harness(router, trace, seed=seed,
                                  drain_timeout_s=300.0, burst=burst)
    finally:
        router.shutdown()
    return summary


def _serve_spec_workload():
    """The SPECULATIVE-DECODING stage behind `bench.py --serve`
    (docs/SERVING.md "Speculative decoding"): a deep-ish target (the
    per-step cost speculation amortizes) and a 1-layer draft run the
    same greedy prompt set non-speculatively and then across an
    accept-rate sweep — draft_temperature 0 (argmax draft, the
    high-accept end) vs a hot noisy draft (the low-accept end), and
    two proposal depths k. Every point reports the accept rate, the
    accepted-tokens-per-verify-step (>1.0 is the whole point — each
    target step yields more than one token), wall-clock
    speedup_vs_nonspec, and the bit-identity verdict
    spec_equals_nonspec (acceptance composes over position-keyed
    draws, so speculation must never change a single token)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
    from paddle_tpu.inference import GenerationEngine, SpeculativeConfig
    from paddle_tpu.jit import warm as jwarm

    n_reqs = int(os.environ.get("BENCH_SERVE_SPEC_REQS", "3"))
    max_new = int(os.environ.get("BENCH_SERVE_SPEC_NEW", "16"))
    layers = int(os.environ.get("BENCH_SERVE_SPEC_LAYERS", "12"))
    # the target must be expensive RELATIVE to the draft and to host
    # dispatch overhead (~7ms/step on CPU), or wall clock measures the
    # scheduler instead of the arithmetic speculation saves — hence a
    # deep/wide target (~54ms/step) against a 1-layer thin draft
    # (dispatch-floor cost)
    # small vocab on purpose: draft/target argmax agreement (the
    # accept rate) falls with vocab size between randomly-initialized
    # models, and vocab only adds head FLOPs — the compute the target
    # amortizes lives in hidden/layers
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=512,
                    num_layers=layers, num_heads=8,
                    max_position_embeddings=128, dropout=0.0)
    target = GPTForCausalLM(cfg)
    target.eval()
    # seed 5 picked by scanning draft inits for argmax agreement with
    # the target's greedy stream (~0.8): a random-init stand-in for
    # the distilled draft that provides the high-accept regime in
    # production — the sweep's low-accept end comes from the hot
    # draft_temperature point, not from a badly-paired draft
    paddle.seed(5)
    dcfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                     num_heads=4, max_position_embeddings=128,
                     dropout=0.0)
    draft = GPTForCausalLM(dcfg)
    draft.eval()
    rng = np.random.RandomState(3)
    # ONE prompt length: one warm schedule to compile, and the stage's
    # point is decode-phase arithmetic, not prefill shape variety
    prompts = [rng.randint(0, 256, (8,)) for _ in range(n_reqs)]

    def run(spec):
        eng = GenerationEngine(
            target, n_pages=128, page_size=8, max_batch=4,
            max_new_tokens=max_new, prefill_chunk=16,
            prefix_cache=False,
            name="bench_spec" if spec else "bench_nonspec",
            speculative=spec)
        try:
            # warm OUTSIDE the timed region (target + draft schedules),
            # then one untimed SHAKEOUT pass: warm's contract covers
            # single-request (B=1) signatures, and this stage batches
            # up to 4 rows — the shakeout compiles the multi-row
            # buckets through the model-level executable cache so the
            # timed pass measures dispatch, not tracing
            jwarm.join(eng.warm_async(prompts[0].size, max_new))
            for h in [eng.submit(p, max_new_tokens=max_new)
                      for p in prompts]:
                h.result(timeout=600)
            t0 = time.perf_counter()
            handles = [eng.submit(p, max_new_tokens=max_new)
                       for p in prompts]
            outs = [h.result(timeout=600).tolist() for h in handles]
            wall = time.perf_counter() - t0
            rep = eng.load_report()
        finally:
            eng.shutdown()
        return outs, wall, rep

    ref_outs, ref_wall, _ = run(None)
    gen_tokens = sum(len(o) for o in ref_outs) \
        - sum(p.size for p in prompts)

    sweep = []
    for k, dt in ((4, 0.0), (2, 0.0), (4, 4.0)):
        spec = SpeculativeConfig(draft, k=k, draft_temperature=dt)
        outs, wall, rep = run(spec)
        proposed = rep["proposed_tokens"]
        accepted = rep["accepted_tokens"]
        # each verify row emits 1 + (its accepted drafts) tokens;
        # rows propose k_eff <= k, so ceil(proposed/k) bounds the row
        # count from below — the per-step figure is conservative
        verify_steps = max(-(-proposed // k), 1)
        sweep.append({
            "k": k, "draft_temperature": dt,
            "accept_rate": round(rep["accept_rate"], 4),
            "proposed_tokens": proposed,
            "accepted_tokens": accepted,
            "accepted_tokens_per_step": round(
                1.0 + accepted / verify_steps, 3),
            "wall_s": round(wall, 3),
            "speedup_vs_nonspec": round(ref_wall / wall, 3)
            if wall else 0.0,
            "spec_equals_nonspec": outs == ref_outs,
        })
    best = max(sweep, key=lambda p: p["accept_rate"])
    return {
        "prompts": n_reqs, "max_new_tokens": max_new,
        "target_layers": layers, "draft_layers": 1,
        "nonspec_wall_s": round(ref_wall, 3),
        "nonspec_tokens_per_s": round(gen_tokens / ref_wall, 1)
        if ref_wall else 0.0,
        "sweep": sweep,
        # the headline numbers ride the HIGH-ACCEPT end of the sweep
        "accept_rate": best["accept_rate"],
        "accepted_tokens_per_step": best["accepted_tokens_per_step"],
        "speedup_vs_nonspec": best["speedup_vs_nonspec"],
        "spec_equals_nonspec": all(p["spec_equals_nonspec"]
                                   for p in sweep),
    }


def _serve_ssm_workload():
    """The SECOND-MODEL-FAMILY stage behind `bench.py --serve`
    (docs/SERVING.md "Cache strategies"): a pure-SSM model (models/
    ssm.py, RecurrentStateCache) against a same-width paged GPT at an
    EQUAL cache memory budget. The headline is capacity: a recurrent
    sequence costs one fixed-size state blob regardless of context, so
    the same bytes admit far more concurrent sequences than paged KV
    at long context — reported as concurrent_capacity_ratio alongside
    measured decode tokens/s through the same GenerationEngine path
    (and the hybrid's blended capacity, attention layers paying KV
    while SSM layers stay O(1))."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
    from paddle_tpu.models.ssm import SSMConfig, SSMForCausalLM
    from paddle_tpu.inference import GenerationEngine
    from paddle_tpu.jit import warm as jwarm

    n_reqs = int(os.environ.get("BENCH_SERVE_SSM_REQS", "4"))
    max_new = int(os.environ.get("BENCH_SERVE_SSM_NEW", "16"))
    # the capacity context: how long a conversation each admitted
    # sequence is budgeted for (the paged side pays KV for all of it,
    # the recurrent side pays the same blob no matter what)
    ctx = int(os.environ.get("BENCH_SERVE_SSM_CTX", "4096"))
    budget = int(os.environ.get("BENCH_SERVE_SSM_BUDGET_MB", "64")) \
        * (1 << 20)
    hidden, layers, heads, page_size = 256, 4, 8, 16
    paddle.seed(0)
    gcfg = GPTConfig(vocab_size=256, hidden_size=hidden,
                     num_layers=layers, num_heads=heads,
                     max_position_embeddings=128, dropout=0.0)
    gpt = GPTForCausalLM(gcfg)
    gpt.eval()
    paddle.seed(0)
    scfg = SSMConfig(vocab_size=256, hidden_size=hidden,
                     num_layers=layers, d_state=16, d_conv=4, expand=2,
                     max_position_embeddings=128)
    ssm = SSMForCausalLM(scfg)
    ssm.eval()

    # equal-memory capacity accounting (f32 pools, the same dtype the
    # engines below serve with)
    kv_bytes_per_token = layers * hidden * 2 * 4     # K + V rows
    kv_bytes_per_seq = -(-ctx // page_size) * page_size \
        * kv_bytes_per_token
    probe = ssm.make_paged_cache(4, page_size)
    state_bytes_per_seq = probe.state_bytes_per_slot()
    paged_capacity = budget // kv_bytes_per_seq
    recurrent_capacity = budget // state_bytes_per_seq
    # hybrid (attn_every=2): half the layers pay per-token KV, half
    # pay the fixed blob — the blend long-context serving actually buys
    hyb_kv = (layers // 2) * hidden * 2 * 4
    hyb_bytes_per_seq = -(-ctx // page_size) * page_size * hyb_kv \
        + (state_bytes_per_seq * (layers - layers // 2)) // layers
    hybrid_capacity = budget // hyb_bytes_per_seq

    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, 256, (8,)) for _ in range(n_reqs)]

    def run(model, name):
        eng = GenerationEngine(model, n_pages=64, page_size=page_size,
                               max_batch=4, max_new_tokens=max_new,
                               prefix_cache=False, name=name)
        try:
            jwarm.join(eng.warm_async(prompts[0].size, max_new))
            for h in [eng.submit(p, max_new_tokens=max_new)
                      for p in prompts]:        # untimed shakeout
                h.result(timeout=600)
            t0 = time.perf_counter()
            handles = [eng.submit(p, max_new_tokens=max_new)
                       for p in prompts]
            outs = [h.result(timeout=600).tolist() for h in handles]
            wall = time.perf_counter() - t0
            rep = eng.load_report()
        finally:
            eng.shutdown()
        toks = sum(len(o) for o in outs)
        return {"cache_strategy": rep["cache_strategy"],
                "decode_tokens_per_s": round(toks / wall, 1)
                if wall else 0.0,
                "wall_s": round(wall, 3),
                "retraces_after_warm": eng.retraces}

    gpt_run = run(gpt, "bench_ssm_paged")
    ssm_run = run(ssm, "bench_ssm_recurrent")
    return {
        "prompts": n_reqs, "max_new_tokens": max_new,
        "capacity_context_tokens": ctx,
        "memory_budget_mb": budget >> 20,
        "kv_bytes_per_seq": kv_bytes_per_seq,
        "state_bytes_per_seq": state_bytes_per_seq,
        "paged_capacity": int(paged_capacity),
        "recurrent_capacity": int(recurrent_capacity),
        "hybrid_capacity": int(hybrid_capacity),
        "concurrent_capacity_ratio": round(
            recurrent_capacity / max(paged_capacity, 1), 1),
        "paged": gpt_run, "recurrent": ssm_run,
        "ssm_decode_tokens_per_s": ssm_run["decode_tokens_per_s"],
    }


def _run_serve():
    """`bench.py --serve`: continuous-batching serving micro-benchmark
    (docs/SERVING.md). N concurrent closed-loop client threads drive one
    InferenceEngine; the serial baseline is the same model called
    one-request-at-a-time (the pre-serving Predictor.run pattern).
    Emits ONE JSON line — same driver contract as the training
    bench — with requests/s, p50/p99 latency, mean batch size, pad
    overhead, and the retrace count after bucket warmup (0 is the
    steady-state contract). Runs as a BENCH_CHILD on the axon path
    (the parent seeds the compile cache, budgets, and merges — see
    main); backend init sits under the same SIGALRM guard as the
    training child, because the first device query goes through the
    axon tunnel and blocks forever when the tunnel is wedged."""
    import signal
    import tempfile
    import threading

    init_budget = int(os.environ.get("BENCH_INIT_TIMEOUT", "240"))

    def _init_timeout(signum, frame):
        raise TimeoutError(
            f"TPU backend init did not complete within {init_budget}s "
            "— axon tunnel unreachable (jax.devices() blocked on "
            "recvfrom)")

    signal.signal(signal.SIGALRM, _init_timeout)
    signal.alarm(init_budget)
    _phase("backend_init")
    import jax
    _enable_compile_cache(jax)
    jax.devices()  # force backend init under the alarm
    signal.alarm(0)
    _phase("build")
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import inference
    from paddle_tpu.jit import save as jit_save, InputSpec
    from paddle_tpu.profiler import monitor as _pmon
    _stream_compiles()  # bucket compiles -> bench-phase, like training

    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "8"))
    per_client = int(os.environ.get("BENCH_SERVE_REQS", "40"))
    # dim sizes the win structurally: at 2048 the two [dim, dim] weight
    # matrices (32 MB) make a single-request forward memory-bound, so a
    # batch-8 GEMM reads them ONCE where 8 serial GEMVs read them 8
    # times — the speedup survives 2-CPU scheduling noise
    dim = int(os.environ.get("BENCH_SERVE_DIM", "2048"))
    n_total = clients * per_client
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(dim, dim), nn.Tanh(),
                          nn.Linear(dim, dim))
    prefix = os.path.join(tempfile.mkdtemp(prefix="bench_serve_"),
                          "model")
    jit_save(model, prefix, input_spec=[InputSpec([None, dim],
                                                  "float32")])
    rng = np.random.RandomState(0)
    x = rng.randn(1, dim).astype(np.float32)

    # serial baseline: the pre-serving pattern — ONE Predictor, one
    # request at a time, loaded from the same artifact the engine serves
    _phase("serial_baseline")
    p_serial = inference.create_predictor(inference.Config(prefix))
    p_serial.run([x])  # compile out of the timed region
    t0 = time.perf_counter()
    for _ in range(n_total):
        p_serial.run([x])
    serial_s = time.perf_counter() - t0

    _phase("warm")
    cfg = inference.Config(prefix)
    cfg.enable_serving(batch_sizes=(1, 2, 4, 8), max_wait_ms=2.0,
                       max_queue=max(64, clients * 4))
    pool = inference.PredictorPool(cfg, size=clients)
    engine = cfg._engine_for(pool.retrive(0)._layer)
    warmed = engine.warm(x)
    # execution warmup OUTSIDE the timed region: first runs of the AOT
    # executables (autotune/pager effects) and thread spin-up must not
    # be billed to steady-state throughput
    warm_threads = [threading.Thread(
        target=lambda i=i: pool.retrive(i).run([x]))
        for i in range(clients)]
    for t in warm_threads:
        t.start()
    for t in warm_threads:
        t.join()
    # counters are process-global: snapshot after warm so the headline
    # reports STEADY-phase batch sizes / padding, not warm traffic
    bs0 = _pmon.get_metric("serve.batch_size")
    bs0_count = bs0.count if bs0 else 0
    bs0_sum = bs0.sum if bs0 else 0.0
    pad0 = _pmon.get_metric("serve.pad_tokens")
    pad0_val = int(pad0.value) if pad0 else 0
    _phase("steady", serial_s=serial_s, warmed_buckets=warmed)

    lat, lat_lock, errors = [], threading.Lock(), []

    def client(i):
        try:
            pred = pool.retrive(i)
            mine = []
            for _ in range(per_client):
                t = time.perf_counter()
                pred.run([x])
                mine.append(time.perf_counter() - t)
            with lat_lock:
                lat.extend(mine)
        except Exception as e:
            errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    serve_s = time.perf_counter() - t0

    # mixed long/short GENERATION workload: ragged vs bucketed pad
    # fractions, prefix hit rate, TTFT percentiles (BENCH_SERVE_GEN=0
    # skips; a failure degrades to an error key, never a dead bench)
    gen = None
    if os.environ.get("BENCH_SERVE_GEN", "1") != "0":
        _phase("generate")
        try:
            gen = _serve_gen_workload()
        except Exception as e:
            gen = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
    # disaggregated 2-engine router topology vs single engine at equal
    # chips/slots (BENCH_SERVE_ROUTER=0 skips; failures degrade to an
    # error key, never a dead bench)
    router = None
    if os.environ.get("BENCH_SERVE_ROUTER", "1") != "0":
        _phase("router")
        try:
            router = _serve_router_workload()
        except Exception as e:
            router = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
    # open-loop load stage: seeded 10x-burst trace through a fresh
    # disaggregated router (BENCH_SERVE_LOAD=0 skips; failures degrade
    # to an error key, never a dead bench)
    load = None
    if os.environ.get("BENCH_SERVE_LOAD", "1") != "0":
        _phase("load")
        try:
            load = _serve_load_workload()
        except Exception as e:
            load = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
    # speculative-decoding accept-rate sweep: draft-temperature /
    # depth-k grid vs the non-speculative baseline (BENCH_SERVE_SPEC=0
    # skips; failures degrade to an error key, never a dead bench)
    speculate = None
    if os.environ.get("BENCH_SERVE_SPEC", "1") != "0":
        _phase("speculate")
        try:
            speculate = _serve_spec_workload()
        except Exception as e:
            speculate = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
    # second model family: SSM capacity-at-equal-memory vs paged GPT +
    # decode tokens/s (BENCH_SERVE_SSM=0 skips; failures degrade to an
    # error key, never a dead bench)
    ssm = None
    if os.environ.get("BENCH_SERVE_SSM", "1") != "0":
        _phase("ssm")
        try:
            ssm = _serve_ssm_workload()
        except Exception as e:
            ssm = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
    _phase("done", serve_s=serve_s)

    lat.sort()
    completed = len(lat)  # an errored client aborts its remaining
    # requests — rates must count what actually ran, not n_total, or a
    # failing run would inflate its own throughput
    bs = _pmon.get_metric("serve.batch_size")
    n_batches = (bs.count if bs else 0) - bs0_count
    rows_sum = (bs.sum if bs else 0.0) - bs0_sum
    pad = _pmon.get_metric("serve.pad_tokens")
    pad_elems = (int(pad.value) if pad else 0) - pad0_val
    real_elems = completed * dim
    headline = {
        "metric": "serve_requests_per_sec",
        "value": round(completed / serve_s, 1),
        "unit": "req/s",
        "clients": clients,
        "requests": n_total,
        "completed": completed,
        "p50_ms": round(1e3 * lat[len(lat) // 2], 3) if lat else 0.0,
        "p99_ms": round(1e3 * lat[min(len(lat) - 1,
                                      int(0.99 * len(lat)))], 3)
        if lat else 0.0,
        "mean_batch_size": round(rows_sum / n_batches, 2)
        if n_batches else 0.0,
        "batches": n_batches,
        "pad_token_frac": round(pad_elems / max(pad_elems + real_elems, 1),
                                4),
        "serial_requests_per_sec": round(n_total / serial_s, 1),
        # per-request time ratio: robust to clients aborting early
        "speedup_vs_serial": round(
            (serial_s / n_total) / (serve_s / completed), 3)
        if completed else 0.0,
        "warmed_buckets": warmed,
        "retraces_after_warm": engine.retraces - warmed,
        "on_tpu": jax.default_backend() == "tpu",
        "errors": errors[:3],
        "compile_ledger": _compile_ledger_table(),
        "phases": dict(_PHASES),
    }
    if router is not None:
        headline["router"] = router
        # the front-door acceptance numbers ride in the headline too
        for k in ("router_speedup_vs_single", "router_slo_attainment",
                  "handoff_count", "router_equals_single"):
            if k in router:
                headline[k] = router[k]
    if gen is not None:
        headline["generate"] = gen
        # the memory-observatory baseline rides in the headline too
        for k in ("hbm_peak_bytes", "kv_pool_bytes", "fragmentation"):
            if k in gen:
                headline[k] = gen[k]
    if load is not None:
        headline["load"] = load
        for k in ("goodput_tokens_per_s", "rejected_fraction",
                  "expired_fraction", "peak_in_flight",
                  "pressure_events"):
            if k in load:
                headline[f"load_{k}"] = load[k]
    if speculate is not None:
        headline["speculate"] = speculate
        # the speculative acceptance numbers ride the headline too
        for k in ("accept_rate", "accepted_tokens_per_step",
                  "speedup_vs_nonspec", "spec_equals_nonspec"):
            if k in speculate:
                headline[f"spec_{k}" if not k.startswith("spec_")
                         else k] = speculate[k]
    if ssm is not None:
        headline["ssm"] = ssm
        for k in ("concurrent_capacity_ratio", "recurrent_capacity",
                  "paged_capacity", "ssm_decode_tokens_per_s"):
            if k in ssm:
                headline[f"ssm_{k}" if not k.startswith("ssm_")
                         else k] = ssm[k]
    if gen is not None or router is not None or load is not None \
            or speculate is not None or ssm is not None:
        # serve trajectory ACROSS rounds (the compile_history twin):
        # bench_state.json keeps the last 10 rounds of the headline
        # serving numbers so a regression in pad fraction / prefix hit
        # rate / TTFT is visible without digging through driver logs
        state = _load_state()
        history = state.get("serve_history", [])
        entry = {"utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime()),
                 "req_per_sec": headline["value"]}
        for k in ("pad_token_fraction_ragged",
                  "pad_token_fraction_bucketed", "prefix_hit_rate",
                  "ttft_p50_ms", "ttft_p99_ms",
                  "ragged_equals_bucketed", "slo_attainment",
                  "goodput_tokens_per_s", "wasted_token_fraction",
                  "kv_peak_occupancy", "kv_pool_bytes",
                  "fragmentation", "hbm_peak_bytes"):
            if gen is not None and k in gen:
                entry[k] = gen[k]
        for k in ("router_speedup_vs_single", "router_slo_attainment",
                  "handoff_count", "router_equals_single",
                  "router_ttft_p50_ms", "router_ttft_p99_ms"):
            if router is not None and k in router:
                entry[k] = router[k]
        for k in ("goodput_tokens_per_s", "rejected_fraction",
                  "expired_fraction", "peak_in_flight",
                  "pressure_events", "ttft_p99_s"):
            if load is not None and k in load:
                entry[f"load_{k}"] = load[k]
        for k in ("accept_rate", "accepted_tokens_per_step",
                  "speedup_vs_nonspec", "spec_equals_nonspec"):
            if speculate is not None and k in speculate:
                entry[f"spec_{k}" if not k.startswith("spec_")
                      else k] = speculate[k]
        for k in ("concurrent_capacity_ratio", "recurrent_capacity",
                  "paged_capacity", "hybrid_capacity",
                  "ssm_decode_tokens_per_s"):
            if ssm is not None and k in ssm:
                entry[f"ssm_{k}" if not k.startswith("ssm_")
                      else k] = ssm[k]
        history.append(entry)
        state["serve_history"] = history[-10:]
        _save_state(state)
        headline["serve_history"] = state["serve_history"]
    cfg.disable_serving()
    print(json.dumps(headline), flush=True)


def _stream_child(extra_env, budget):
    """Run this script as a child (BENCH_CHILD=1 plus extra_env), stream
    its output live. ALL child output — JSON lines included — goes to the
    parent's stderr: the driver contract is exactly one stdout JSON line,
    printed once by the parent as its final word. Returns
    (rc, json_lines, stderr_tail, last_phase); rc is 'timeout' when the
    budget killed it; last_phase is the child's most recent
    "bench-phase:" breakdown (dict or None) — present even when a
    timeout killed the child before any JSON."""
    import subprocess
    import threading

    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env["PYTHONUNBUFFERED"] = "1"
    env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        errors="replace")
    json_lines = []
    err_tail = []
    phase_holder = []

    def _pump_out():
        for raw in proc.stdout:
            line = raw.rstrip("\n")
            if line.startswith("{"):
                json_lines.append(line)
            print(line, file=sys.stderr, flush=True)

    def _pump_err():
        for raw in proc.stderr:
            line = raw.rstrip("\n")
            if line.startswith("bench-phase: "):
                try:
                    phase_holder[:] = [
                        json.loads(line[len("bench-phase: "):])]
                except ValueError:
                    pass
            err_tail.append(line)
            del err_tail[:-8]
            print(raw, end="", file=sys.stderr, flush=True)

    t_out = threading.Thread(target=_pump_out, daemon=True)
    t_err = threading.Thread(target=_pump_err, daemon=True)
    t_out.start()
    t_err.start()
    try:
        proc.wait(timeout=budget)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        # SIGTERM first: the child's flight recorder dumps a debug
        # bundle (ring tail + thread stacks — WHERE it hung) on the way
        # down; SIGKILL only if it wedged too hard even for that
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        rc = "timeout"
    t_out.join(timeout=5)
    t_err.join(timeout=5)
    return rc, json_lines, err_tail, \
        (phase_holder[0] if phase_holder else None)


def main():
    """Parent: run each attempt in a SUBPROCESS with a hard wall-clock
    timeout — SIGALRM cannot interrupt a GIL-holding C++ compile RPC
    (observed 2026-07-30: a congested remote compile helper stretched the
    normally-60s compile past 30 min and in-process alarms never fired).
    The child (BENCH_CHILD=1) does the real work and prints the headline
    JSON the instant it is measured (to the parent's stderr stream); the
    parent appends side metrics and prints the merged line ONCE to
    stdout as its final word — the driver contract is exactly one stdout
    JSON line."""
    serve = "--serve" in sys.argv[1:] or \
        os.environ.get("BENCH_TASK") == "serve"
    if serve and os.environ.get("BENCH_CHILD") == "1":
        # serving child: does the real work, prints the headline JSON
        # the instant it is measured; failures print a diagnostic
        try:
            _run_serve()
        except Exception as e:
            print(json.dumps({
                "metric": "serve_requests_per_sec", "value": 0.0,
                "unit": "req/s",
                "error": f"{type(e).__name__}: {str(e)[:400]}",
                "phases": dict(_PHASES),
                "traceback_tail": traceback.format_exc()[-800:]}),
                flush=True)
            raise SystemExit(1)
        return
    if serve:
        # serving PARENT (the training-bench contract, extended to
        # --serve for the axon backend): seed the compile cache from a
        # donated artifact, run the child under a hard wall-clock
        # budget with live output streaming, print the merged headline
        # ONCE to stdout. A wedged axon tunnel or a congested compile
        # helper gets killed and diagnosed (phases name the executable
        # that ate the budget) instead of eating the round — the r04/
        # r05 failure mode, which SIGALRM alone cannot interrupt once
        # a GIL-holding compile RPC is in flight.
        os.environ.setdefault("PADDLE_TPU_DEBUG_DUMP", os.path.join(
            tempfile.gettempdir(), "paddle_tpu_bench_debug"))
        seed_info = _seed_cache()
        budget = int(os.environ.get(
            "BENCH_SERVE_BUDGET",
            os.environ.get("BENCH_ATTEMPT_TIMEOUT", "300")))
        rc, json_lines, err_tail, last_phase = _stream_child(
            {"BENCH_TASK": "serve"}, budget)
        got = None
        for line in json_lines:
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if cand.get("metric") == "serve_requests_per_sec":
                got = cand
        if got is None:
            got = {"metric": "serve_requests_per_sec", "value": 0.0,
                   "unit": "req/s",
                   "error": f"serving child produced no headline "
                            f"(rc={rc})",
                   "evidence": [s[:300] for s in err_tail[-3:]],
                   "child_phases": last_phase}
        got["serve_budget_s"] = budget
        if seed_info is not None:
            got["cache_seed"] = seed_info
        print(json.dumps(got), flush=True)
        if got.get("error"):
            raise SystemExit(1)
        return
    if os.environ.get("BENCH_CHILD") == "1":
        try:
            if os.environ.get("BENCH_TASK") == "1p3b":
                _run_1p3b()
                return
            _run()
        except Exception as e:
            tb = traceback.format_exc()
            # flight-recorder debug bundle: ring tail + HLO of every
            # compiled train step + all-thread stacks — the evidence a
            # 0.0 headline needs (requires paddle_tpu to have imported)
            bundle = None
            try:
                from paddle_tpu.profiler import flight_recorder as _fr
                bundle = _fr.dump("bench_failure", exc=e)
            except Exception:
                pass
            print(json.dumps({
                "metric": "gpt_medium_train_tokens_per_sec_per_chip",
                "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {str(e)[:400]}",
                # how far the attempt got and what each phase cost — the
                # diagnosis BENCH_r05's bare 0.0 lacked
                "phases": dict(_PHASES),
                "debug_bundle": bundle,
                "traceback_tail": tb[-800:]}), flush=True)
            raise SystemExit(1)
        return

    # crash/hang debuggability for the child attempts: give them a dump
    # dir (unless the operator already points one elsewhere), so a
    # failed/timed-out attempt leaves a flight-recorder bundle — the
    # child dumps on its own exceptions; a timeout kill's SIGTERM
    # triggers the flight recorder's signal dump
    os.environ.setdefault("PADDLE_TPU_DEBUG_DUMP", os.path.join(
        tempfile.gettempdir(), "paddle_tpu_bench_debug"))

    t_start = time.perf_counter()
    total_budget = int(os.environ.get("BENCH_TOTAL_BUDGET", "480"))

    def remaining():
        return total_budget - (time.perf_counter() - t_start)

    # BENCH_CACHE_SEED: a donated compile-cache artifact pre-populates
    # the cache before any attempt — a seeded round's compiles are
    # loads, so the first attempt finishes fast and its unused budget
    # rolls over to the runtime-record config below
    seed_info = _seed_cache()

    # Attempt order (round-7): scan+names FIRST, always — one lowered
    # block body is the compile-bound default that gets A headline past
    # the compile wall; the unrolled config (fastest at runtime, r3
    # record, but the longest cold compile) runs second on whatever
    # budget the first attempt left over (rollover below). With a
    # warm/seeded cache both load in seconds and the parent reports the
    # best.
    scan_cfg = {}  # child defaults: scan=1 remat=names
    unrolled = {"BENCH_SCAN": "0", "BENCH_REMAT": "false"}
    pinned = "BENCH_REMAT" in os.environ or "BENCH_SCAN" in os.environ
    attempts = [{}] if pinned else [scan_cfg, unrolled]

    def _last_json(lines, pred):
        got = None
        for line in lines:
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if pred(cand):
                got = cand
        return got

    def _evidence(json_lines, err_tail):
        # bounded per-string so the diagnostic JSON can never be cut
        # mid-structure into unparseable output
        return [s[:300] for s in (json_lines[-1:] or err_tail[-3:])]

    best = None
    failures = []
    trajectory = []
    attempt_cap = int(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "300"))
    carry = 0.0  # unused seconds roll over to the next attempt
    for extra in attempts:
        if best is not None and remaining() < 90:
            break  # keep what we have rather than risk the budget
        if best is not None and not best.get("on_tpu"):
            break  # off-TPU the configs are identical smoke runs
        env_view = dict(os.environ)
        env_view.update(extra)
        tag = f"scan={env_view.get('BENCH_SCAN', '1')}" \
              f",remat={env_view.get('BENCH_REMAT', 'names')}"
        # rollover budgeting: a fast (cache-seeded) first attempt's
        # unused seconds fund the next attempt instead of evaporating
        # into the old fixed per-attempt cap
        budget = _attempt_budget(attempt_cap, carry, remaining())
        if budget < 60:
            # budget floor: launching an attempt the driver will kill
            # anyway would overrun BENCH_TOTAL_BUDGET — record why and
            # fall through to the diagnostic-failure JSON below
            failures.append({
                "attempt": tag, "rc": "not_launched",
                "budget_s": round(max(budget, 0)),
                "evidence": [f"total budget exhausted "
                             f"({round(remaining())}s remaining)"]})
            break
        t_attempt = time.perf_counter()
        rc, json_lines, err_tail, last_phase = _stream_child(extra, budget)
        carry = max(0.0, budget - (time.perf_counter() - t_attempt))
        result = _last_json(
            json_lines,
            lambda c: c.get("metric") and c.get("value", 0) > 0)
        # phase breakdown even for a timed-out child (streamed over
        # stderr) or a crashed one (embedded in its diagnostic JSON)
        diag = _last_json(json_lines, lambda c: "phases" in c)
        phases = (result or diag or {}).get("phases") or last_phase or {}
        # per-attempt compile trajectory — recorded success, crash, and
        # timeout alike: the per-executable compiles that finished, the
        # one still compiling when the attempt died (the bench-phase
        # stream keeps both through SIGKILL), and the attempt's compile
        # seconds (the full warmup when it got that far, else the sum
        # of the finished compiles)
        compiles = phases.get("compiles") or []
        compile_s = phases.get("compile_warmup_s")
        if compile_s is None:
            compile_s = round(sum(c.get("lower_s", 0.0)
                                  + c.get("compile_s", 0.0)
                                  for c in compiles), 2)
        trajectory.append({
            "attempt": tag,
            "rc": "ok" if result else rc,
            "budget_s": round(budget),
            "compile_s": compile_s,
            "cache_hit": bool(phases.get("compile_cache_hit", False)),
            "compiling": phases.get("compiling"),
            "compiles": compiles[-8:],
        })
        if result:
            if best is None or result["value"] > best["value"]:
                best = result
        else:
            fail = {"attempt": tag, "rc": rc, "budget_s": round(budget),
                    "evidence": _evidence(json_lines, err_tail),
                    # where this attempt's flight-recorder bundle (ring
                    # tail, HLO, thread stacks) landed — if it got far
                    # enough to write one
                    "debug_bundle": os.environ["PADDLE_TPU_DEBUG_DUMP"]}
            if phases:
                fail["phases"] = phases
            failures.append(fail)

    # compile-seconds trajectory ACROSS rounds: append this round's
    # attempts to the state file's bounded history, so round N+1's
    # headline (and a human reading bench_state.json) sees the compile
    # wall shrinking — or not — over time
    state = _load_state()
    history = state.get("compile_history", [])
    history.append({
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "cache_seeded": bool(seed_info
                             and seed_info.get("entries_seeded")),
        "attempts": [{k: t[k] for k in
                      ("attempt", "rc", "compile_s", "cache_hit")}
                     for t in trajectory]})
    state["compile_history"] = history[-10:]
    _save_state(state)

    if best is None:
        print(json.dumps({
            "metric": "gpt_medium_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": "all attempts failed (compile congestion?)",
            "attempts": failures,
            "cache_seed": seed_info,
            "compile_trajectory": trajectory,
            "compile_history": state["compile_history"]}), flush=True)
        raise SystemExit(1)
    best["cache_seeded"] = bool(seed_info
                                and seed_info.get("entries_seeded"))
    if seed_info:
        best["cache_seed"] = seed_info
    best["compile_trajectory"] = trajectory
    best["compile_history"] = state["compile_history"]

    # flagship side metric, strictly after the headline is safe and only
    # with budget to spare; its JSON goes to stderr so a kill mid-run
    # can never leave a metric-less fragment as the last stdout line
    best.setdefault("gpt_1p3b_tokens_per_sec", 0.0)
    best.setdefault("gpt_1p3b_mfu", 0.0)
    if best.get("on_tpu") and os.environ.get("BENCH_1P3B", "1") == "1" \
            and remaining() > 120:
        b13 = max(60, min(int(os.environ.get("BENCH_1P3B_TIMEOUT", "420")),
                          remaining() - 30))
        env13 = {"BENCH_TASK": "1p3b"}
        if "BENCH_1P3B_REMAT" not in os.environ:
            env13["BENCH_1P3B_REMAT"] = "dots"  # round-4 sweep winner
        rc, json_lines, err_tail, _ = _stream_child(env13, b13)
        got = _last_json(json_lines,
                         lambda c: "gpt_1p3b_tokens_per_sec" in c)
        if got:
            best.update(got)
        else:
            best["gpt_1p3b_error"] = (
                f"rc={rc} budget={round(b13)}s " +
                " | ".join(_evidence(json_lines, err_tail)))[:300]
    if failures:
        best["attempt_failures"] = str(failures)[:500]
    print(json.dumps(best), flush=True)


if __name__ == "__main__":
    main()
