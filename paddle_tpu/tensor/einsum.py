"""Einstein summation. Parity: python/paddle/tensor/einsum.py.

jnp.einsum lowers directly to XLA dot_general — MXU-friendly by
construction, so unlike the reference (which plans and decomposes into
matmul/transpose ops: tensor/einsum.py:~800) we delegate planning to XLA.
"""
import jax.numpy as jnp

from ..framework.core import apply_op


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return apply_op(lambda *xs: jnp.einsum(equation, *xs), *operands)
