"""GPT scan-over-blocks path: lax.scan over stacked per-layer params must
be numerically identical to the unrolled python loop (fwd + grads), and
the eager tape path must keep working (scan is gated to traced contexts).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.api import functional_call, state_arrays
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no


def _setup():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=3,
                    num_heads=2, max_position_embeddings=32, dropout=0.0)
    m = GPTForCausalLM(cfg)
    params, _ = state_arrays(m)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (2, 16)), jnp.int32)
    return cfg, m, params, ids


class TestGPTScanBlocks:
    def test_forward_matches_unrolled(self):
        cfg, m, params, ids = _setup()

        def fwd(params, ids):
            return functional_call(m, params, {}, (ids,), training=False)

        cfg.scan_layers = True
        out_scan = jax.jit(fwd)(params, ids)
        cfg.scan_layers = False
        out_unroll = jax.jit(fwd)(params, ids)
        np.testing.assert_allclose(np.asarray(out_scan),
                                   np.asarray(out_unroll),
                                   rtol=1e-6, atol=1e-6)

    @pytest.mark.heavy

    def test_grads_match_unrolled_and_remat(self):
        cfg, m, params, ids = _setup()

        def loss(params, scan, remat=False):
            cfg.scan_layers, cfg.scan_remat = scan, remat
            logits = functional_call(m, params, {}, (ids,), training=True)
            return jnp.mean(jax.nn.logsumexp(
                logits.astype(jnp.float32), -1))

        g_un = jax.grad(lambda p: loss(p, False))(params)
        for remat in (False, True):
            g_scan = jax.grad(lambda p: loss(p, True, remat))(params)
            for k in g_un:
                np.testing.assert_allclose(
                    np.asarray(g_scan[k]), np.asarray(g_un[k]),
                    rtol=1e-5, atol=1e-6, err_msg=f"{k} remat={remat}")

    def test_eager_tape_still_works(self):
        cfg, m, params, ids = _setup()
        cfg.scan_layers = True  # gated off outside traces
        t = paddle.to_tensor(np.asarray(ids))
        l = m.loss(t, t)
        l.backward()
        assert m.parameters()[0].grad is not None
        assert np.isfinite(float(l.item()))


class TestStaticCacheGenerate:
    """generate() must compile exactly two programs (prefill + scanned
    decode) and match a naive full-recompute greedy loop."""

    def _model(self):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=64,
                        dropout=0.0)
        return GPTForCausalLM(cfg), cfg

    @pytest.mark.heavy
    def test_matches_naive_greedy(self):
        import jax
        import jax.numpy as jnp
        m, cfg = self._model()
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 128, (2, 7)).astype(np.int64))
        out = m.generate(ids, max_new_tokens=5, temperature=1e-4)
        assert out.shape == [2, 12]
        # naive loop: argmax over full forward each step
        cur = ids.numpy()
        for _ in range(5):
            logits = m(paddle.to_tensor(cur)).numpy()
            nxt = logits[:, -1, :].argmax(-1)[:, None]
            cur = np.concatenate([cur, nxt], axis=1)
        np.testing.assert_array_equal(out.numpy(), cur)

    @pytest.mark.heavy

    def test_two_compiled_programs(self):
        m, cfg = self._model()
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 128, (1, 4)).astype(np.int64))
        m.generate(ids, max_new_tokens=8)
        m.generate(ids, max_new_tokens=8)  # same shapes: reuse
        assert len(m._gen_jit) == 1
        pre, dec = next(iter(m._gen_jit.values()))
        assert pre is not None and dec is not None

    def test_prompt_plus_tokens_over_max_pos_rejected(self):
        m, cfg = self._model()
        ids = paddle.to_tensor(np.zeros((1, 60), np.int64))
        with pytest.raises(ValueError):
            m.generate(ids, max_new_tokens=10)


class TestTopPSampling:
    def test_nucleus_restricts_support(self):
        """With a known logit distribution (p=0.6/0.3/0.1), top_p=0.7
        must only ever sample the first two tokens."""
        import jax
        import jax.numpy as jnp
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=8, hidden_size=16, num_layers=1,
                        num_heads=2, max_position_embeddings=32,
                        dropout=0.0)
        m = GPTForCausalLM(cfg)
        # hijack the head: force logits so token probs are known.
        # p ~ softmax([log .6, log .3, log .1, -inf x5])
        target = np.log(np.array([0.6, 0.3, 0.1], np.float32))

        class Fixed:
            pass

        def fake_forward(ps, ids, kbs=None, vbs=None, pos=None):
            pass

        # easier: test the sampling math directly through generate by
        # monkeypatching functional_call is brittle; instead replicate
        # the sample fn's nucleus logic here and check it matches the
        # implementation choice (prefix mass < top_p keeps the token)
        arr = jnp.asarray(np.concatenate(
            [target, np.full(5, -1e30, np.float32)]))[None, :]
        srt = jnp.sort(arr, axis=-1)[:, ::-1]
        p_srt = jax.nn.softmax(srt, axis=-1)
        before = jnp.cumsum(p_srt, axis=-1) - p_srt
        keep = before < 0.7
        thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)
        masked = jnp.where(arr >= thresh, arr, -1e30)
        key = jax.random.PRNGKey(0)
        draws = jax.random.categorical(key, jnp.tile(masked, (512, 1)))
        assert set(np.asarray(draws).tolist()) <= {0, 1}

    def test_generate_with_top_p_runs(self):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                        num_heads=2, max_position_embeddings=32,
                        dropout=0.0)
        m = GPTForCausalLM(cfg)
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            0, 64, (1, 4)).astype(np.int64))
        out = m.generate(ids, max_new_tokens=5, top_p=0.9)
        assert out.shape == [1, 9]
        assert (out.numpy() >= 0).all() and (out.numpy() < 64).all()
