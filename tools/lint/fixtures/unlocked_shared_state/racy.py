"""Known-bad corpus for the unlocked-shared-state pass.

The dict-changed-size-during-unlocked-snapshot class: a scheduler
thread mutates per-engine state that a caller-thread report method
iterates with no lock in scope."""
import threading


class RacyEngine:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats = {}
        self._done = []
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            # unlocked mutation on the scheduler thread
            self._stats["steps"] = self._stats.get("steps", 0) + 1
            self._done.append(self._stats["steps"])

    def load_report(self):
        # unlocked snapshot from the caller's thread: dict(...) can
        # throw "dictionary changed size during iteration"
        return dict(self._stats), len(self._done)


class AnnotatedRacy:
    """The same race spelled with a type annotation: ast.AnnAssign
    writes must be as visible to the pass as plain assignments."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._thread = threading.Thread(target=self._tick, daemon=True)
        self._thread.start()

    def _tick(self):
        while True:
            self._count: int = self._count + 1  # unlocked annotated write

    def snapshot(self):
        return self._count
