"""Chunked vocab-projection + softmax cross-entropy.

The LM loss is the single biggest activation on a big-vocab model: the
full logits tensor [B*T, V] (f32: 1.6 GB at B*T=8192, V=50304) plus its
log-softmax and gradient. This op never materializes it: a lax.scan over
token chunks computes `h_chunk @ W^T -> logsumexp -> gold logit` with
jax.checkpoint around the chunk body, so the backward pass RECOMPUTES
each chunk's logits from the (tiny) saved hidden chunk instead of saving
[n_chunks, chunk, V]. Peak live logits memory drops from O(B*T*V) to
O(chunk*V).

Reference counterpart: paddle's fused softmax_with_cross_entropy kernel
(paddle/fluid/operators/softmax_with_cross_entropy_op.cu) fuses the
softmax with the loss but still takes materialized logits; the chunking
over the VOCAB PROJECTION is the TPU-native extension that makes
single-chip billion-param training fit.

Numerics note (measured, v5e): chained bf16 matmul + f32 logsumexp per
chunk matches the unchunked f32 reference to ~1e-3 relative — the same
precision class as the unchunked bf16 path.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["chunked_softmax_xent", "softmax_xent_logits"]


def softmax_xent_logits(logits, labels, ignore_index=-100,
                        shard_axis=None):
    """Per-token softmax cross-entropy from materialized logits,
    formulated GATHER-FREE: the gold logit is `sum(one_hot(y) * logits)`
    instead of a take_along_axis. Under GSPMD with the vocab dim sharded
    (`shard_axis='mp'`), that is the difference between a partial
    product-sum per shard (+ a tiny cross-shard add, like the logsumexp
    reductions) and a dynamic gather the partitioner can only lower by
    ALL-GATHERING the full [N, V] logits to every device. A sharding
    constraint is applied on the vocab dim so the partitioner keeps the
    logits distributed through the whole loss (the mechanism behind
    ParallelCrossEntropy; reference counterpart:
    c_softmax_with_cross_entropy, which masks per-shard ids and
    allreduces by hand).

    logits: [..., V] float; labels: int [...] (ignore_index masks).
    Returns per-token loss [...] in f32, 0.0 at masked positions.
    """
    def constrain(arr):
        if shard_axis is None:
            return arr
        try:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..distributed.env import get_mesh
            if isinstance(arr, jax.core.Tracer):
                spec = P(*([None] * (arr.ndim - 1) + [shard_axis]))
                return lax.with_sharding_constraint(
                    arr, NamedSharding(get_mesh(), spec))
        except Exception:
            pass
        return arr

    v = logits.shape[-1]
    lg = constrain(logits).astype(jnp.float32)
    lg = constrain(lg)
    m = lax.stop_gradient(jnp.max(lg, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(lg - m), axis=-1)) + jnp.squeeze(m, -1)
    y = labels.astype(jnp.int32)
    if y.ndim == lg.ndim:  # [..., 1]-style labels
        y = jnp.squeeze(y, -1)
    valid = y != ignore_index
    safe = jnp.where(valid, y, 0)
    onehot = constrain(jax.nn.one_hot(safe, v, dtype=jnp.float32))
    gold = jnp.sum(onehot * lg, axis=-1)
    return jnp.where(valid, lse - gold, 0.0)


def _pick_chunk(n, target=2048):
    """Largest divisor of n that is <= target (prefers big MXU tiles)."""
    c = min(target, n)
    while n % c:
        c -= 1
    return max(c, 1)


@functools.partial(jax.jit, static_argnames=("chunk", "transpose_w"))
def _impl(h, w, labels, chunk, transpose_w):
    N = h.shape[0]
    n_chunks = N // chunk
    h_c = h.reshape(n_chunks, chunk, h.shape[-1])
    y_c = labels.reshape(n_chunks, chunk)

    @jax.checkpoint
    def chunk_loss(hc, yc):
        logits = (hc @ w.T if transpose_w else hc @ w).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, yc[:, None], axis=-1)[:, 0]
        valid = yc >= 0  # ignore_index=-100 style masking
        return (jnp.sum(jnp.where(valid, lse - gold, 0.0)),
                jnp.sum(valid.astype(jnp.float32)))

    def body(carry, xs):
        s, n = carry
        ds, dn = chunk_loss(*xs)
        return (s + ds, n + dn), None

    (total, count), _ = lax.scan(body, (jnp.zeros((), jnp.float32),
                                        jnp.zeros((), jnp.float32)),
                                 (h_c, y_c))
    return total / jnp.maximum(count, 1.0)


def chunked_softmax_xent(hidden, weight, labels, chunk=2048,
                         transpose_w=True):
    """Mean token cross-entropy of `softmax(hidden @ weight^T)` vs labels.

    hidden: [N, H] (bf16/f32), weight: [V, H] (transpose_w=True, the
    weight-tied wte layout) or [H, V], labels: int [N] (negative = ignore).
    Fully differentiable; O(chunk*V) live logits.
    """
    n = hidden.shape[0]
    c = _pick_chunk(n, chunk)
    return _impl(hidden, weight, labels, c, transpose_w)
