"""Static-KV-cache text generation: exactly two compiled programs
(prefill + scanned decode) regardless of --tokens.

    python examples/generate_gpt.py --tokens 64
"""
import argparse
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=32)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--top-k", type=int, default=40)
    args = ap.parse_args()

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                    num_heads=4, max_position_embeddings=256, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()

    prompt = paddle.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(args.batch, 8)).astype(np.int64))
    t0 = time.perf_counter()
    out = model.generate(prompt, max_new_tokens=args.tokens,
                         temperature=0.8, top_k=args.top_k)
    dt = time.perf_counter() - t0
    print(f"{args.batch}x{args.tokens} tokens in {dt:.2f}s "
          f"(compile included; {len(model._gen_jit)} program set(s))")
    t0 = time.perf_counter()
    model.generate(prompt, max_new_tokens=args.tokens, temperature=0.8,
                   top_k=args.top_k)
    print(f"warm: {time.perf_counter() - t0:.3f}s")
    print(out.numpy()[:, :16])


if __name__ == "__main__":
    main()
