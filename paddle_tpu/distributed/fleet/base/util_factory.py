"""fleet.util. Parity: python/paddle/distributed/fleet/base/util_factory.py
(UtilBase: small cross-worker helpers used by training scripts).

Collective ops ride the jax mesh (distributed/collective.py); file-shard
and print helpers are plain Python.
"""
import os

import numpy as np

__all__ = ["UtilBase", "UtilFactory"]


class UtilBase:
    def __init__(self, role_maker=None):
        self._role_maker = role_maker

    def _rank_world(self):
        # process-level topology: in single-controller SPMD one process
        # feeds all its local devices, so IO sharding splits by process
        import jax
        return jax.process_index(), jax.process_count()

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        """Reduce a numpy value across workers. Single-process worlds
        (the TPU SPMD model: one process, many chips) return the input."""
        rank, world = self._rank_world()
        arr = np.asarray(input)
        if world <= 1:
            return arr
        from ...collective import all_reduce as _ar, ReduceOp
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        import paddle_tpu as paddle
        t = paddle.to_tensor(arr)
        _ar(t, op=op)
        return np.asarray(t.numpy())

    def barrier(self, comm_world="worker"):
        from ... import env
        env.barrier()

    def all_gather(self, input, comm_world="worker"):
        rank, world = self._rank_world()
        if world <= 1:
            return [input]
        from ...collective import all_gather as _ag
        import paddle_tpu as paddle
        out = []
        _ag(out, paddle.to_tensor(np.asarray(input)))
        return [np.asarray(t.numpy()) for t in out]

    def get_file_shard(self, files):
        """Split a file list contiguously across workers
        (ref behavior: first `len(files) % world` workers get one extra)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file names")
        rank, world = self._rank_world()
        if self._role_maker is not None:
            rank = self._role_maker.worker_index()
            world = self._role_maker.worker_num()
        base, extra = divmod(len(files), world)
        counts = [base + (1 if i < extra else 0) for i in range(world)]
        start = sum(counts[:rank])
        return files[start:start + counts[rank]]

    def print_on_rank(self, message, rank_id):
        rank, _ = self._rank_world()
        if self._role_maker is not None:
            rank = self._role_maker.worker_index()
        if rank == rank_id:
            print(message)


class UtilFactory:
    def _create_util(self, context=None):
        return UtilBase(None if context is None
                        else context.get("role_maker"))
