"""Common functionals: linear, dropout, padding, interpolate, etc.
Parity: python/paddle/nn/functional/common.py."""
import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op
from ...framework.random import split_key


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W shaped [in, out] (paddle layout). Pure MXU work;
    under amp.auto_cast the matmul runs in the policy dtype (bf16) — the
    cast is baked at record time by apply_op(op_name=...) so backward
    replays with identical dtypes."""
    def fn(a, w, *rest):
        out = a @ w
        if rest:
            out = out + rest[0].astype(out.dtype)
        return out
    if bias is None:
        return apply_op(fn, x, weight, op_name="linear")
    return apply_op(fn, x, weight, bias, op_name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply_op(lambda a: a * (1.0 - p), x)
        return x
    def fn(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(a.shape)]
        keep = jax.random.bernoulli(split_key(), 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return apply_op(fn, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a_coef = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
    b_coef = -a_coef * p * alpha_p
    def fn(a):
        keep = jax.random.bernoulli(split_key(), 1.0 - p, a.shape)
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)
    return apply_op(fn, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    def fn(a):
        nd = a.ndim
        if len(pad) == 2 * nd:  # full per-dim spec (paddle "NCHW all dims")
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle convention: pad covers spatial dims, reversed order
            # (last dim first), like torch.nn.functional.pad
            n_spatial = len(pad) // 2
            widths = [(0, 0)] * nd
            if data_format.endswith("C"):  # NHWC-style: spatial before C
                spatial_axes = list(range(1, 1 + n_spatial))
            else:
                spatial_axes = list(range(nd - n_spatial, nd))
            for i, ax in enumerate(reversed(spatial_axes)):
                widths[ax] = (pad[2 * i], pad[2 * i + 1])
        if jmode == "constant":
            return jnp.pad(a, widths, mode=jmode, constant_values=value)
        return jnp.pad(a, widths, mode=jmode)
    return apply_op(fn, x)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0,
               data_format=data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply_op(fn, x1, x2)


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *rest):
        out = jnp.einsum("bm,omn,bn->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    if bias is not None:
        return apply_op(fn, x1, x2, weight, bias)
    return apply_op(fn, x1, x2, weight)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    mode = mode.lower()
    if isinstance(size, Tensor):
        size = [int(v) for v in size.numpy()]
    if size is not None and not isinstance(size, (list, tuple)):
        size = [int(size)]
    def fn(a):
        channel_last = data_format.endswith("C")
        nd = a.ndim
        n_spatial = nd - 2
        sf = scale_factor
        if sf is not None and not isinstance(sf, (list, tuple)):
            sf = [sf] * n_spatial  # scalar factor scales EVERY spatial dim
        sp_axes = list(range(1, 1 + n_spatial)) if channel_last \
            else list(range(2, nd))
        in_sizes = [a.shape[i] for i in sp_axes]
        if size is not None:
            out_sizes = [int(s) for s in size]
        else:
            out_sizes = [int(round(s * f))
                         for s, f in zip(in_sizes, sf)]
        out_shape = list(a.shape)
        for ax, s in zip(sp_axes, out_sizes):
            out_shape[ax] = s
        method = {"nearest": "nearest", "bilinear": "linear",
                  "trilinear": "linear", "linear": "linear",
                  "bicubic": "cubic", "area": "linear"}[mode]
        if mode == "nearest" or not align_corners:
            return jax.image.resize(a, out_shape, method=method
                                    ).astype(a.dtype)
        # align_corners: gather with exact corner-aligned coordinates
        out = a
        for ax, osz in zip(sp_axes, out_sizes):
            isz = out.shape[ax]
            if isz == osz:
                continue
            pos = jnp.linspace(0.0, isz - 1.0, osz)
            lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, isz - 1)
            hi = jnp.clip(lo + 1, 0, isz - 1)
            w = (pos - lo).astype(a.dtype)
            shape = [1] * out.ndim
            shape[ax] = osz
            w = w.reshape(shape)
            out = jnp.take(out, lo, axis=ax) * (1 - w) + \
                jnp.take(out, hi, axis=ax) * w
        return out.astype(a.dtype)
    return apply_op(fn, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def aslist(v, n=2):
        return list(v) if isinstance(v, (list, tuple)) else [v] * n
    k = aslist(kernel_sizes)
    s = aslist(strides)
    p = aslist(paddings) if isinstance(paddings, (list, tuple)) \
        else [paddings] * 2
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    d = aslist(dilations)

    def fn(a):
        N, C, H, W = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])])
        oh = (a.shape[2] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (a.shape[3] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                sl = a[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                       j * d[1]: j * d[1] + ow * s[1]: s[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # N,C,k0*k1,oh,ow
        return out.reshape(N, C * k[0] * k[1], oh * ow)
    return apply_op(fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def aslist(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 2
    out_hw = aslist(output_sizes)
    k = aslist(kernel_sizes)
    s = aslist(strides)
    p = aslist(paddings)
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    d = aslist(dilations)

    def fn(a):
        N, CKK, L = a.shape
        C = CKK // (k[0] * k[1])
        H = out_hw[0] + p[0] + p[2]
        W = out_hw[1] + p[1] + p[3]
        oh = (H - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (W - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        a4 = a.reshape(N, C, k[0], k[1], oh, ow)
        out = jnp.zeros((N, C, H, W), a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                             j * d[1]: j * d[1] + ow * s[1]: s[1]].add(
                    a4[:, :, i, j])
        return out[:, :, p[0]: H - p[2], p[1]: W - p[3]]
    return apply_op(fn, x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l, *rest):
        k = l.shape[-1]
        if rest:
            return (1 - epsilon) * l + epsilon * rest[0]
        return (1 - epsilon) * l + epsilon / k
    if prior_dist is not None:
        return apply_op(fn, label, prior_dist)
    return apply_op(fn, label)
