"""Worker script for test_launch_multiproc.py — run via
`python -m paddle_tpu.distributed.launch --nnodes 2 --node_rank R
 --master 127.0.0.1:PORT tests/_launch_worker.py OUTDIR`.

Each process pins the CPU backend (1 local device), joins the 2-process
jax.distributed world through paddle_tpu.distributed.init_parallel_env,
runs a cross-process psum and a small data-parallel train step, and
writes its observations to OUTDIR/rank<r>.json for the parent to check.
"""
import json
import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly 1 local CPU device per proc

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa


def main():
    outdir = sys.argv[1]
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    rank = dist.get_rank()
    world = jax.process_count()
    assert world == 2, f"expected 2 processes, got {world}"
    assert jax.device_count() == 2, jax.device_count()

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    # 1. cross-process collective: psum of the rank id
    from paddle_tpu.framework.jax_compat import shard_map

    @jax.jit
    def allsum(x):
        return shard_map(
            lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
            in_specs=P("dp"), out_specs=P())(x)

    local = np.array([float(rank)], dtype=np.float32)
    global_x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local, (2,))
    summed = float(np.asarray(jax.device_get(allsum(global_x))))

    # 2. DP train step: replicated params, per-process batch shard, psum'd
    # grads -> params must end identical on both ranks
    rs = np.random.RandomState(0)  # SAME init on both ranks
    w0 = rs.randn(8, 1).astype(np.float32)
    Xall = rs.randn(16, 8).astype(np.float32)
    Yall = Xall @ np.full((8, 1), 0.5, np.float32)
    # each process holds its half of the global batch
    Xloc = Xall[rank * 8:(rank + 1) * 8]
    Yloc = Yall[rank * 8:(rank + 1) * 8]
    Xg = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), Xloc, (16, 8))
    Yg = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), Yloc, (16, 1))
    rep = NamedSharding(mesh, P())
    w = jax.device_put(jnp.asarray(w0), rep)

    @jax.jit
    def step(w, X, Y):
        def loss_fn(w_):
            return jnp.mean((X @ w_ - Y) ** 2)
        l, g = jax.value_and_grad(loss_fn)(w)
        return l, w - 0.1 * g   # XLA inserts the dp grad psum

    losses = []
    for _ in range(5):
        l, w = step(w, Xg, Yg)
        losses.append(float(np.asarray(jax.device_get(l))))

    with open(os.path.join(outdir, f"rank{rank}.json"), "w") as f:
        json.dump({"rank": rank, "world": world, "psum": summed,
                   "losses": losses,
                   "w": np.asarray(jax.device_get(w)).tolist()}, f)


if __name__ == "__main__":
    main()
