"""Pipeline layer descriptions. Parity:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py
(PipelineLayer / LayerDesc / SharedLayerDesc).

The reference materializes only the local stage's layers per rank and
moves activations with NCCL p2p. TPU-native design: PipelineLayer keeps
the full logical stack and partitions it into `num_stages` segments; the
PipelineParallel engine (pipeline_parallel.py) stacks per-stage params and
runs all stages in SPMD over the 'pp' mesh axis, rotating microbatch
activations with lax.ppermute (GPipe schedule — fill, steady state, drain
— expressed as one lax.scan).
"""
import numpy as np

from ....nn.layer.layers import Layer
from ....nn.layer.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Holds the full layer stack + its partition into stages."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        descs = list(layers)
        built = []
        self._shared = {}
        for d in descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append((layer, d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, "fn"))
            else:
                raise TypeError(f"unsupported pipeline item {d!r}")
        self.run_function = built
        self._layers_list = LayerList(
            [l for l, tag in built if isinstance(l, Layer)])

        if topology is not None:
            self._num_stages = topology.get_dim("pipe")
        else:
            self._num_stages = num_stages or 1
        n = len(built)
        per = -(-n // self._num_stages)
        self.segments = [built[i * per:(i + 1) * per]
                         for i in range(self._num_stages)]
        self.recompute_interval = recompute_interval

    @property
    def num_stages(self):
        return self._num_stages

    def get_stage_layers(self, stage_id):
        return self.segments[stage_id]

    def forward(self, x):
        """Reference semantics: run the whole stack (single-device path)."""
        for item, tag in self.run_function:
            if tag == "fn":
                x = item(x)
            elif tag is not None and tag != "fn":
                x = tag(item, x)
            else:
                x = item(x)
        return x

    def loss(self, x, label):
        out = self.forward(x)
        if self._loss_fn is None:
            raise RuntimeError("PipelineLayer built without loss_fn")
        return self._loss_fn(out, label)
