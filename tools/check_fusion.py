#!/usr/bin/env python
"""Fusion-regression gate: per-executable fusion counts and
bytes-accessed vs the checked-in BASELINE_HLO.json.

Why (ROADMAP open item 4; *Operator Fusion in XLA*, arxiv 2301.13062):
XLA's fusion decisions are the difference between one fused region and
a memory-bound chain of materialized intermediates — and they silently
change when a model edit, a new op, or a sharding constraint breaks a
fusion boundary. XLA's own `cost_analysis()` bytes-accessed and the
optimized HLO's fusion count (recorded per executable by
profiler/compile_observatory.py) are the regression signals; like
tools/check_no_hot_sync.py, this gate fails loudly and names the
executable instead of letting a fusion break land as a vague slowdown.

Comparison: per baseline tag, FAIL when

    fusion_count   >  baseline + FUSION_SLACK   (default 0: same
                      container, same flags — the HLO is deterministic;
                      MORE fusion regions means a region broke apart)
    bytes_accessed >  baseline * (1 + BYTES_TOL) (default 10%)
    instructions   >  baseline + INSTR_SLACK    (default 0: the HLO
                      instruction count is deterministic; growth is the
                      per-leaf op-soup signature the fused multi-tensor
                      epilogue exists to prevent — a tree-path
                      regression shows up here as hundreds of extra
                      tiny ops before it shows up in seconds)

Sources and ratcheting: identical to tools/check_compile_budget.py
(--ledger JSONL or the canonical workload; `--update` only ever
tightens). tests/test_compile_observatory.py runs this gate from
tier-1: green on the checked-in baseline, nonzero (naming the
executable) on an injected fusion/bytes regression.

Usage:
  python tools/check_fusion.py [--baseline BASELINE_HLO.json]
         [--ledger FILE.jsonl] [--fusion-slack 0] [--bytes-tol 0.10]
         [--instr-slack 0] [--require-all] [--update]
Exit 0 clean, 1 on regression, 2 on gate failure.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _gate_common as gc  # noqa: E402


def compare(baseline, current, fusion_slack, bytes_tol, require_all,
            instr_slack=0):
    """(violations, notes, ratchet) — ratchet maps tag -> better entry."""
    violations, notes, ratchet = [], [], {}
    base_tags = baseline["executables"]
    for tag in sorted(base_tags):
        base = base_tags[tag]
        cur = current.get(tag)
        if cur is None:
            msg = (f"{tag}: in baseline but not in the ledger (renamed "
                   "executable? partial ledger?)")
            (violations if require_all else notes).append(msg)
            continue
        base_fusion = int(base.get("fusion_count", 0))
        base_bytes = float(base.get("bytes_accessed", 0.0))
        base_instr = int(base.get("instructions", 0))
        if cur["fusion_count"] > base_fusion + fusion_slack:
            violations.append(
                f"{tag}: fusion_count {cur['fusion_count']} > baseline "
                f"{base_fusion} (+{fusion_slack} slack) — a fused "
                "region broke apart; diff the HLO in the debug bundle "
                "or compiled_text()")
        if base_bytes and cur["bytes_accessed"] > \
                base_bytes * (1.0 + bytes_tol):
            violations.append(
                f"{tag}: bytes_accessed {cur['bytes_accessed']:.3e} > "
                f"baseline {base_bytes:.3e} * {1.0 + bytes_tol:.2f} — "
                "the executable moves more HBM bytes per run")
        if base_instr and cur["instructions"] > base_instr + instr_slack:
            violations.append(
                f"{tag}: instructions {cur['instructions']} > baseline "
                f"{base_instr} (+{instr_slack} slack) — per-leaf op "
                "soup is creeping back; check what stopped going "
                "through the fused epilogue / fused kernels")
        strictly_better = (cur["fusion_count"] < base_fusion or
                           cur["bytes_accessed"] < base_bytes or
                           (base_instr and
                            cur["instructions"] < base_instr))
        no_worse = (cur["fusion_count"] <= base_fusion and
                    cur["bytes_accessed"] <= base_bytes and
                    (not base_instr or
                     cur["instructions"] <= base_instr))
        if strictly_better and no_worse:
            ratchet[tag] = cur
            notes.append(
                f"{tag}: fusion {cur['fusion_count']} / bytes "
                f"{cur['bytes_accessed']:.3e} / instr "
                f"{cur['instructions']} beats baseline "
                f"{base_fusion} / {base_bytes:.3e} / {base_instr} "
                "(ratchet with --update)")
    for tag in sorted(set(current) - set(base_tags)):
        notes.append(f"{tag}: new executable with no fusion baseline — "
                     "add it with --update")
        ratchet[tag] = current[tag]
    return violations, notes, ratchet


def main(argv=None):
    ap = argparse.ArgumentParser(
        "check_fusion",
        description="per-executable fusion count + bytes-accessed vs "
                    "BASELINE_HLO.json")
    ap.add_argument("--baseline", default=gc.BASELINE_DEFAULT)
    ap.add_argument("--ledger", default=None,
                    help="metrics JSONL with kind:'compile' records; "
                         "default: run the canonical workload")
    ap.add_argument("--fusion-slack", type=int, default=int(
        os.environ.get("PADDLE_TPU_FUSION_SLACK", "0")))
    ap.add_argument("--bytes-tol", type=float, default=float(
        os.environ.get("PADDLE_TPU_BYTES_TOL", "0.10")))
    ap.add_argument("--instr-slack", type=int, default=int(
        os.environ.get("PADDLE_TPU_INSTR_SLACK", "0")))
    ap.add_argument("--require-all", action="store_true",
                    help="every baseline executable must appear in the "
                         "ledger (canonical-workload ledgers)")
    ap.add_argument("--update", action="store_true",
                    help="ratchet: rewrite baseline entries the current "
                         "run beats; add unbudgeted tags")
    args = ap.parse_args(argv)

    try:
        baseline = gc.load_baseline(args.baseline)
        if args.ledger:
            current = gc.aggregate(
                gc.load_compile_records(args.ledger))
        else:
            with tempfile.TemporaryDirectory() as td:
                current = gc.run_workload(
                    os.path.join(td, "ledger.jsonl"))
    except (gc.GateError, OSError) as e:
        print(f"check_fusion: {e}", file=sys.stderr)
        return 2

    violations, notes, ratchet = compare(
        baseline, current, args.fusion_slack, args.bytes_tol,
        args.require_all, instr_slack=args.instr_slack)

    print("fusion accounting (per executable):")
    for tag in sorted(current):
        cur = current[tag]
        base = baseline["executables"].get(tag, {})
        print(gc.format_row(tag, [
            f"fusions {cur['fusion_count']:4d}"
            f" (base {base.get('fusion_count', '-')})",
            f"bytes {cur['bytes_accessed']:.3e}"
            f" (base {float(base.get('bytes_accessed', 0.0)):.3e})",
            f"instr {cur['instructions']:5d}"
            f" (base {base.get('instructions', '-')})"]))
    for n in notes:
        print(f"note: {n}")
    if args.update and ratchet:
        for tag, cur in ratchet.items():
            # rewrite ONLY this gate's comparands (HLO shape: fusions /
            # bytes / instructions / flops); the compile seconds stay
            # whatever check_compile_budget last ratcheted — fewer
            # fusions must not launder a slower compile into the shared
            # baseline. A NEW tag records the full row.
            existing = baseline["executables"].get(tag)
            entry = dict(existing or {})
            entry.update({
                "fusion_count": int(cur["fusion_count"]),
                "bytes_accessed": float(cur["bytes_accessed"]),
                "instructions": int(cur["instructions"]),
                "flops": float(cur["flops"])})
            if existing is None:
                entry.update({
                    "lower_s": round(cur["lower_s"], 3),
                    "compile_s": round(cur["compile_s"], 3),
                    "total_s": round(cur["total_s"], 3)})
            baseline["executables"][tag] = entry
        gc.save_baseline(args.baseline, baseline)
        print(f"ratcheted {len(ratchet)} entr(y/ies) -> {args.baseline}")
    for v in violations:
        print(f"FAIL: {v}")
    if violations:
        print(f"FAIL: {len(violations)} fusion regression(s)")
        return 1
    print(f"OK: {len(current)} executable(s) match the fusion baseline")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
