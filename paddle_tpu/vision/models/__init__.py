"""Parity: python/paddle/vision/models/__init__.py."""
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, wide_resnet50_2, wide_resnet101_2)
from .small_nets import (LeNet, AlexNet, alexnet, VGG, vgg11, vgg13, vgg16,
                         vgg19, SqueezeNet, squeezenet1_0, squeezenet1_1)
from .mobilenet import (MobileNetV1, mobilenet_v1, MobileNetV2,
                        mobilenet_v2, ShuffleNetV2, shufflenet_v2_x1_0)
