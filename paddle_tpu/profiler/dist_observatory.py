"""The distributed observatory: collective telemetry, rank-skew and
straggler detection, coordinator clock alignment, and measured
device-time MFU.

Third observatory sibling (after `compile_observatory.py` and
`serve_observatory.py`), built for the layer the other two cannot see:
what happens BETWEEN ranks. PR 2's `@_instrumented` collective wrappers
count calls and bytes; this module adds the time dimension and the
cross-rank dimension, which is the measurement prerequisite for
productionizing pipeline parallelism (ROADMAP open item 2's success
metric — "overlap measured in the Perfetto trace" — is unevaluable
without it). Four pieces:

- **Per-collective timing** — every `paddle.distributed` collective
  call folds into an in-memory per-op rollup (calls / bytes / wall
  seconds: two dict ops, hot-loop safe), and a SAMPLED subset (first
  call per op, then every `PADDLE_TPU_COLLECTIVE_SAMPLE`-th) emits a
  full `kind:"collective"` record — op, process group (mesh axis),
  payload bytes, wall seconds, derived bus bandwidth GB/s — ringed in
  the flight recorder always, JSONL when configured. Calls made UNDER
  TRACE (inside jit/shard_map) are insertion sites, not executions:
  they fold into the rollup flagged `traced` and their records carry
  `traced: true` with `bw_gbps: 0` (the device-side time of an
  in-graph collective belongs to the XLA trace, not host wall clock).

- **Rank-skew / straggler detection** — `emit_rankstat()` publishes a
  periodic per-rank `kind:"rankstat"` record (step-time p50/p99 from
  the `train.step_s` reservoir, `host_blocked_s`, eager
  collective-wait share, peak device bytes, the rank's clock offset),
  and — when `PADDLE_TPU_RANKSTAT_DIR` names a shared directory
  (`distributed.launch --log_dir` sets it) — atomically snapshots it
  to `rankstat.<rank>.json`. Rank 0 reads the peer snapshots at the
  same cadence (file reads OFF the hot path — cadence-gated, never
  per step) and feeds them to `health.AnomalyDetector.observe_ranks`,
  which emits an edge-triggered `kind:"event"` `event:"straggler"`
  naming the rank and its lag when one trails the group median.

- **Clock alignment** — `clock_sync()` runs a coordinator handshake at
  `init_parallel_env` (barrier, then every rank stamps `time.time()`
  and publishes it through the jax.distributed KV store): each rank's
  offset vs rank 0's clock is estimated once, stamped onto every
  exported record (`monitor.set_clock_offset`) and into every exported
  trace's `otherData.clock_offset_s` — `tools/merge_traces.py`
  subtracts it so a merged Perfetto timeline shows real cross-rank
  overlap (collective lanes lining up across pids) instead of skewed
  starts.

- **Measured device time** — a sampled probe (every
  `PADDLE_TPU_DEVICE_TIME_EVERY` steps; `0` disables) in the train-step
  dispatch paths drains the in-flight step, dispatches, and blocks
  until the new step's output is ready: the window IS the device step
  time, free of async-dispatch pipelining. Both blocking reads live in
  `jit/api.py` / `hybrid_train.py` under explicit `hot-sync-ok`
  cadence-gate markers (`tools/check_no_hot_sync.py` fences this whole
  module and those regions). Each probe yields `step_time_device_s`,
  `mfu_measured` (XLA cost-analysis FLOPs over MEASURED time — the
  companion the cost-analysis MFU never had), and an
  `overlap_fraction` (share of the measured window NOT spent in
  host-visible eager collective waits), carried in the step record,
  the bench headline, and the multichip dryrun output.

See docs/OBSERVABILITY.md "The distributed observatory".
"""
import collections
import json
import math
import os
import threading
import time

from . import monitor as _monitor

__all__ = ["record_collective", "collective_rollup", "eager_wait_s",
           "collectives_tail", "clock_sync", "clock_offset_s",
           "maybe_rankstat", "emit_rankstat", "rankstats_tail",
           "read_peer_rankstats", "device_probe_due",
           "record_device_time", "device_time_summary", "reset",
           "COLLECTIVE_RING", "RANKSTAT_RING", "DEVICE_RING"]

COLLECTIVE_RING = 256  # sampled collective records kept in process
RANKSTAT_RING = 64     # recent rankstat records (host_stats / bundles)
DEVICE_RING = 64       # recent device-time probe results

_lock = threading.RLock()
_coll = {}  # op -> {"calls", "bytes", "wall_s", "traced_calls",
            #        "traced_wall_s"}
_coll_ring = collections.deque(maxlen=COLLECTIVE_RING)
_rank_ring = collections.deque(maxlen=RANKSTAT_RING)
_device_ring = collections.deque(maxlen=DEVICE_RING)
_state = {"clock_offset_s": 0.0, "clock_rtt_s": None,
          "rankstat_emitted": False, "detector": None}


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# -- per-collective timing ----------------------------------------------

def record_collective(op, group, nbytes, wall_s, traced=False):
    """One collective call (the `@_instrumented` wrappers in
    distributed/collective.py call this): ALWAYS folds into the per-op
    rollup (two dict ops — hot-loop safe), and the sampled subset
    (first call per op, then every PADDLE_TPU_COLLECTIVE_SAMPLE-th,
    default 16) emits the full `kind:"collective"` record. Never
    raises — telemetry must not take down a collective."""
    try:
        wall_s = max(wall_s, 0.0) * 1.0  # host arithmetic, no sync
        nbytes = max(int(nbytes), 0)
        with _lock:
            agg = _coll.get(op)
            if agg is None:
                agg = _coll[op] = {"calls": 0, "bytes": 0, "wall_s": 0.0,
                                   "traced_calls": 0, "traced_wall_s": 0.0}
            agg["calls"] += 1
            agg["bytes"] += nbytes
            if traced:
                agg["traced_calls"] += 1
                agg["traced_wall_s"] += wall_s
            else:
                agg["wall_s"] += wall_s
            n = agg["calls"]
        every = _env_int("PADDLE_TPU_COLLECTIVE_SAMPLE", 16)
        if every <= 0 or (n != 1 and n % every != 0):
            return None
        bw = 0.0
        if not traced and wall_s > 0 and nbytes > 0:
            bw = nbytes / wall_s / 1e9
        if not math.isfinite(bw):
            bw = 0.0
        rec = {"op": str(op), "group": str(group), "bytes": nbytes,
               "wall_s": round(wall_s, 9), "bw_gbps": round(bw, 4),
               "traced": bool(traced), "calls": n}
        _monitor.export_step(rec, kind="collective")
        with _lock:
            _coll_ring.append(dict(rec))
        return rec
    except Exception:
        return None


def collective_rollup():
    """{op: {"calls", "bytes", "wall_s", "traced_calls",
    "traced_wall_s"}} — the cumulative per-op aggregate every call
    folds into (the cheap always-on view; records are the sampled
    detail)."""
    with _lock:
        return {k: dict(v) for k, v in _coll.items()}


def eager_wait_s():
    """Total host wall seconds spent inside EAGER collective calls
    (traced insertion time excluded) — the numerator of the rankstat
    collective-wait share and the device probe's overlap fraction."""
    with _lock:
        return sum(v["wall_s"] for v in _coll.values())


def collectives_tail():
    """The ring of recent sampled `kind:"collective"` records (oldest
    first) — what host_stats.json embeds as `collectives`."""
    with _lock:
        return [dict(r) for r in _coll_ring]


# -- clock alignment -----------------------------------------------------

def clock_sync(client=None, rank=None, world=None, timeout_ms=20000):
    """Estimate this rank's wall-clock offset vs rank 0 through the
    jax.distributed coordinator: all ranks meet at a barrier, stamp
    `time.time()` immediately after release, publish the stamp through
    the KV store, and read rank 0's — `offset_s = t_local - t_rank0`
    (positive = this clock runs ahead). Up to barrier-release skew,
    simultaneous events across ranks then satisfy
    `wall - offset_s == rank0 wall`, which is exactly the correction
    `tools/merge_traces.py` applies. The offset is stamped onto every
    subsequently exported record (`monitor.set_clock_offset`) and a
    `kind:"event"` `clock_sync` event carries the handshake evidence.
    Called from `init_parallel_env` for multi-process worlds; never
    raises (a failed handshake leaves offset 0 = unaligned, same as
    before this module existed). Returns the offset, or None when the
    handshake could not run."""
    try:
        if client is None:
            from jax._src import distributed as _jdist
            client = _jdist.global_state.client
            if rank is None:
                rank = _jdist.global_state.process_id
        if client is None:
            return None
        rank = int(rank or 0)
        client.wait_at_barrier("paddle_tpu_clock_sync", timeout_ms)
        t_local = time.time()
        client.key_value_set(f"paddle_tpu_clock/{rank}", repr(t_local))
        t_req = time.perf_counter()
        t0 = float(client.blocking_key_value_get("paddle_tpu_clock/0",  # hot-sync-ok: parsing the KV-store string (init-time handshake, not a device read)
                                                 timeout_ms))
        rtt = time.perf_counter() - t_req
        offset = t_local - t0
        with _lock:
            _state["clock_offset_s"] = offset
            _state["clock_rtt_s"] = rtt
        _monitor.set_clock_offset(offset)
        from . import flight_recorder as _flight
        _flight.record_event("clock_sync", rank=rank,
                             world=int(world or 0),
                             offset_s=round(offset, 6),
                             rtt_s=round(rtt, 6))
        return offset
    except Exception:
        return None


def clock_offset_s():
    """This rank's estimated wall-clock offset vs rank 0 (seconds; 0.0
    single-controller or before/without a handshake). Exported traces
    carry it as `otherData.clock_offset_s`."""
    with _lock:
        return _state["clock_offset_s"] * 1.0


# -- rank-skew / straggler detection -------------------------------------

def _rank_world():
    for var in ("PADDLE_TPU_NUM_PROCESSES", "PADDLE_TRAINERS_NUM"):
        v = os.environ.get(var)
        if v:
            try:
                return max(int(v), 1)
            except ValueError:
                pass
    return 1


def _rankstat_dir():
    return os.environ.get("PADDLE_TPU_RANKSTAT_DIR") or None


def maybe_rankstat(step_i):
    """Cadence gate for the per-step call sites (`export_step_metrics`):
    emit a rankstat on the FIRST step seen and then every
    PADDLE_TPU_RANKSTAT_EVERY-th (default 16; 0 disables). The
    off-cadence cost is one int modulo."""
    every = _env_int("PADDLE_TPU_RANKSTAT_EVERY", 16)
    if every <= 0:
        return None
    if _state["rankstat_emitted"] and step_i % every != 0:
        return None
    return emit_rankstat(step=step_i)


def emit_rankstat(step=None, force=False):
    """Build + export ONE `kind:"rankstat"` record for this rank:
    step-time p50/p99 (the `train.step_s` reservoir), host_blocked_s,
    eager collective wait and its share of run wall time, peak device
    bytes, and the clock offset. With PADDLE_TPU_RANKSTAT_DIR set the
    record is also snapshotted (atomic tmp+rename) to
    `rankstat.<rank>.json` for the rank-0 gather, and rank 0 reads the
    peer snapshots and feeds the straggler detector. Never raises;
    returns the record (None on failure, or when rankstat telemetry is
    disabled — PADDLE_TPU_RANKSTAT_EVERY=0 — and the caller did not
    `force`: the epoch-boundary emit in Model.fit must respect the
    off switch; the canonical gate workload / dryrun force)."""
    if not force and _env_int("PADDLE_TPU_RANKSTAT_EVERY", 16) <= 0:
        return None
    try:
        rank = _monitor.rank()
        world = _rank_world()
        hist = _monitor.get_metric("train.step_s")
        p50 = hist.percentile(50) if hist is not None else 0.0
        p99 = hist.percentile(99) if hist is not None else 0.0
        n_steps = int(hist.count) if hist is not None else 0
        step_wall = hist.sum if hist is not None else 0.0
        coll_wait = eager_wait_s()
        # share of this rank's stepped wall time spent waiting at eager
        # collectives; clamped — the schema pins it to [0, 1]
        share = min(coll_wait / step_wall, 1.0) if step_wall > 0 else 0.0
        try:
            from .. import device as _device
            peak = int(_device.max_memory_allocated())
        except Exception:
            peak = 0
        rec = {
            "step": int(step if step is not None else n_steps),
            "world_size": int(world),
            "steps_observed": n_steps,
            "step_time_p50_s": round(p50, 6),
            "step_time_p99_s": round(max(p99, p50), 6),
            "host_blocked_s": round(_monitor.host_blocked_s(), 6),
            "collective_wait_s": round(coll_wait, 6),
            "collective_wait_share": round(share, 6),
            "peak_bytes": peak,
            "clock_offset_s": round(clock_offset_s(), 6),
        }
        _state["rankstat_emitted"] = True
        _monitor.export_step(rec, kind="rankstat")
        _monitor.counter("dist.rankstats").inc()
        with _lock:
            _rank_ring.append(dict(rec, rank=rank))
        d = _rankstat_dir()
        if d:
            _snapshot_rankstat(d, rank, rec)
            if rank == 0:
                _gather_and_detect(d, rec)
        return rec
    except Exception:
        return None


def _snapshot_rankstat(d, rank, rec):
    """Atomically publish this rank's latest rankstat into the shared
    gather directory (tmp + os.replace: a reader never sees a torn
    file)."""
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"rankstat.{rank}.json")
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(dict(rec, rank=rank, ts=time.time()), f)
        os.replace(tmp, path)
    except OSError:
        pass


def read_peer_rankstats(d=None):
    """{rank: latest rankstat record} from the shared gather dir —
    what rank 0 feeds the straggler detector (and what a debug bundle
    or obs_report can read post-hoc). Unreadable/torn files are
    skipped."""
    d = d or _rankstat_dir()
    out = {}
    if not d or not os.path.isdir(d):
        return out
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("rankstat.") and name.endswith(".json")):
            continue
        try:
            r = int(name[len("rankstat."):-len(".json")])
            with open(os.path.join(d, name)) as f:
                out[r] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


def _detector():
    det = _state["detector"]
    if det is None:
        from .health import AnomalyDetector
        det = _state["detector"] = AnomalyDetector()
    return det


def _gather_and_detect(d, own_rec):
    """Rank 0's gather: read every peer's latest rankstat snapshot and
    feed per-rank step-time p50s to the straggler detector. Runs only
    at rankstat cadence (never per step) — file reads stay off the hot
    path. A peer whose snapshot has not advanced since the last gather
    still participates (its p50 is its honest current estimate) — but
    a snapshot older than PADDLE_TPU_RANKSTAT_STALE_S (default 600 s)
    or from a rank outside the CURRENT world is skipped: an elastic
    restart reusing the log_dir, or a dead rank's frozen file, must
    not feed phantom stragglers forever."""
    peers = read_peer_rankstats(d)
    now = time.time()
    peers[0] = dict(own_rec, rank=0, ts=now)
    world = _rank_world()
    stale_s = max(_env_int("PADDLE_TPU_RANKSTAT_STALE_S", 600), 1)
    rank_times = {r: rec.get("step_time_p50_s", 0.0) * 1.0
                  for r, rec in peers.items()
                  if r < world
                  and now - rec.get("ts", now) < stale_s
                  and rec.get("steps_observed", rec.get("step", 0))}
    if len(rank_times) >= 2:
        events = _detector().observe_ranks(
            int(own_rec.get("step", 0)), rank_times)
        if events:
            _monitor.counter("dist.stragglers").inc(len(events))
        return events
    return []


def rankstats_tail():
    """The ring of this process's recent rankstat records (oldest
    first) — what host_stats.json embeds as `rankstats`."""
    with _lock:
        return [dict(r) for r in _rank_ring]


# -- measured device time ------------------------------------------------

def device_probe_due(step_i):
    """Whether the device-time probe should run at this step — one int
    modulo per step (PADDLE_TPU_DEVICE_TIME_EVERY, default 16; 0
    disables). The probe's two blocking reads live at the call sites
    in jit/api.py / hybrid_train.py under explicit hot-sync-ok cadence
    markers; this module stays sync-free."""
    every = _env_int("PADDLE_TPU_DEVICE_TIME_EVERY", 16)
    return every > 0 and step_i % every == 0


def record_device_time(step_obj, step_i, dt, info, coll_wait0=None,
                       drain_s=0.0):
    """Fold one device-time probe window into the observatory:
    `dt` is the measured drain→dispatch→ready wall window (= device
    step time, pipelining excluded), `info` the step executable's
    compile info (cost-analysis flops), `coll_wait0` the eager
    collective-wait total captured when the window opened. Publishes
    the `train.step_time_device_s` / `train.mfu_measured` /
    `train.overlap_fraction` gauges, rings the sample, and leaves the
    values on `step_obj._last_device_probe` for `export_step_metrics`
    to carry in the SAME step's record. Never raises."""
    try:
        from . import cost as _cost
        dt = max(dt, 0.0) * 1.0
        flops = (info.get("flops", 0.0) or 0.0) if info else 0.0
        m = _cost.mfu(flops, dt)
        coll = 0.0
        if coll_wait0 is not None:
            coll = max(eager_wait_s() - coll_wait0, 0.0)
        overlap = 1.0 - min(coll / dt, 1.0) if dt > 0 else 0.0
        probe = {"step": int(step_i),
                 "step_time_device_s": round(dt, 6),
                 "mfu_measured": round(m, 6),
                 "overlap_fraction": round(overlap, 6),
                 # the probe's artificial drain wait — what
                 # export_step_metrics subtracts from the probed step's
                 # inter-dispatch interval (never exported)
                 "probe_drain_s": max(drain_s, 0.0) * 1.0}
        step_obj._last_device_probe = probe
        _monitor.gauge("train.step_time_device_s").set(dt)
        _monitor.gauge("train.mfu_measured").set(m)
        _monitor.gauge("train.overlap_fraction").set(overlap)
        with _lock:
            _device_ring.append(dict(probe))
        return probe
    except Exception:
        return None


def device_time_summary():
    """Median-of-samples rollup of the probe ring: {"samples",
    "step_time_device_s", "mfu_measured", "overlap_fraction"} — what
    the bench headline and the multichip dryrun report. {} when no
    probe has fired."""
    with _lock:
        samples = [dict(r) for r in _device_ring]
    if not samples:
        return {}

    def med(key):
        vals = sorted(r[key] for r in samples)
        return vals[len(vals) // 2]

    return {"samples": len(samples),
            "step_time_device_s": med("step_time_device_s"),
            "mfu_measured": med("mfu_measured"),
            "overlap_fraction": med("overlap_fraction")}


def reset():
    """Drop rollups, rings, detector state, and the clock offset
    (tests)."""
    with _lock:
        _coll.clear()
        _coll_ring.clear()
        _rank_ring.clear()
        _device_ring.clear()
        _state.update({"clock_offset_s": 0.0, "clock_rtt_s": None,
                       "rankstat_emitted": False, "detector": None})
    _monitor.set_clock_offset(0.0)
