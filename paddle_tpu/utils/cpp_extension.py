"""paddle.utils.cpp_extension. Parity: python/paddle/utils/cpp_extension/.

The reference JIT-compiles CUDA/C++ custom operators against the paddle
runtime. TPU-native equivalent: custom *host* ops compile to a shared
library bound via ctypes (see paddle_tpu/runtime for the in-tree example);
custom *device* ops should be written as Pallas kernels (paddle_tpu/ops) —
there is no stable TPU ISA to hand-compile against.
"""
import ctypes
import os
import subprocess
import tempfile

__all__ = ["load", "CppExtension", "CUDAExtension", "BuildExtension",
           "get_build_directory"]


def get_build_directory():
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.expanduser("~/.cache/paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def load(name, sources, extra_cxx_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None, build_directory=None,
         verbose=False):
    """Compile C++ sources into a shared lib and return a ctypes handle."""
    build_dir = build_directory or get_build_directory()
    so_path = os.path.join(build_dir, f"lib{name}.so")
    srcs = [s for s in sources if s.endswith((".cc", ".cpp", ".cxx"))]
    if not srcs:
        raise ValueError("cpp_extension.load needs C++ sources "
                         "(CUDA sources are not applicable on TPU)")
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not os.path.exists(so_path) or os.path.getmtime(so_path) < newest_src:
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread"]
        for inc in (extra_include_paths or []):
            cmd += ["-I", inc]
        cmd += (extra_cxx_cflags or [])
        cmd += srcs + ["-o", so_path] + (extra_ldflags or [])
        if verbose:
            print("+", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(so_path)


class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(sources, *args, **kwargs):
    raise NotImplementedError(
        "CUDAExtension has no TPU analogue; write device code as Pallas "
        "kernels (paddle_tpu.ops) and host code via CppExtension")


class BuildExtension:
    @staticmethod
    def with_options(**options):
        return BuildExtension
