"""MobileNetV1/V2 + ShuffleNetV2. Parity:
python/paddle/vision/models/{mobilenetv1,mobilenetv2,shufflenetv2}.py."""
from ... import nn
from ...tensor.manipulation import flatten, concat, split

__all__ = ["MobileNetV1", "mobilenet_v1", "MobileNetV2", "mobilenet_v2",
           "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]


def _conv_bn(in_c, out_c, k, stride=1, padding=0, groups=1):
    return nn.Sequential(
        nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(out_c), nn.ReLU6())


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        s = lambda c: max(int(c * scale), 8)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
            [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, s(32), 3, stride=2, padding=1)]
        for in_c, out_c, stride in cfg:
            layers.append(_conv_bn(s(in_c), s(in_c), 3, stride=stride,
                                   padding=1, groups=s(in_c)))
            layers.append(_conv_bn(s(in_c), s(out_c), 1))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.fc(flatten(x, 1))


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn(inp, hidden, 1))
        layers += [
            _conv_bn(hidden, hidden, 3, stride=stride, padding=1,
                     groups=hidden),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = int(32 * scale)
        layers = [_conv_bn(3, in_c, 3, stride=2, padding=1)]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                layers.append(_InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        last = int(1280 * max(1.0, scale))
        layers.append(_conv_bn(in_c, last, 1))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.classifier = nn.Sequential(nn.Dropout(0.2),
                                        nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.classifier(flatten(x, 1))


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out_c // 2
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), act_layer())
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), act_layer(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), act_layer())
        self.shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return self.shuffle(out)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        stage_out = {0.25: [24, 48, 96, 512], 0.33: [32, 64, 128, 512],
                     0.5: [48, 96, 192, 1024], 1.0: [116, 232, 464, 1024],
                     1.5: [176, 352, 704, 1024],
                     2.0: [244, 488, 976, 2048]}[scale]
        self.conv1 = _conv_bn(3, 24, 3, stride=2, padding=1)
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        in_c = 24
        stages = []
        for i, repeats in enumerate([4, 8, 4]):
            out_c = stage_out[i]
            units = [_ShuffleUnit(in_c, out_c, 2, act=act)]
            for _ in range(repeats - 1):
                units.append(_ShuffleUnit(out_c, out_c, 1, act=act))
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn(in_c, stage_out[3], 1)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(stage_out[3], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        return self.fc(flatten(self.pool(x), 1))


def _shufflenet(scale, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network access; load a local "
            "state_dict via model.set_state_dict instead")
    return ShuffleNetV2(scale=scale, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained, act="swish", **kwargs)
