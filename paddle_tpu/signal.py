"""paddle.signal. Parity: python/paddle/signal.py (frame/overlap_add/stft/istft)."""
import math

import numpy as np
import jax.numpy as jnp

from .framework.core import Tensor, apply_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    def fn(a):
        n = a.shape[axis]
        num = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[:, None] +
               hop_length * jnp.arange(num)[None, :])
        out = jnp.take(a, idx.reshape(-1), axis=axis)
        shp = list(a.shape)
        if axis == -1 or axis == a.ndim - 1:
            shp = shp[:-1] + [frame_length, num]
        else:
            shp = [frame_length, num] + shp[1:]
        return out.reshape(shp)
    return apply_op(fn, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    def fn(a):
        if axis in (-1, a.ndim - 1):
            fl, num = a.shape[-2], a.shape[-1]
            n = (num - 1) * hop_length + fl
            out = jnp.zeros(a.shape[:-2] + (n,), a.dtype)
            for i in range(num):
                out = out.at[..., i * hop_length:i * hop_length + fl].add(
                    a[..., :, i])
            return out
        fl, num = a.shape[0], a.shape[1]
        n = (num - 1) * hop_length + fl
        out = jnp.zeros((n,) + a.shape[2:], a.dtype)
        for i in range(num):
            out = out.at[i * hop_length:i * hop_length + fl].add(a[:, i])
        return out
    return apply_op(fn, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wv = window.value if isinstance(window, Tensor) else (
        jnp.asarray(window) if window is not None
        else jnp.ones(win_length))
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        wv = jnp.pad(wv, (pad, n_fft - win_length - pad))

    def fn(a):
        if center:
            widths = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, widths, mode=pad_mode)
        n = a.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[:, None] +
               hop_length * jnp.arange(num)[None, :])
        frames = a[..., idx]                       # [..., n_fft, num]
        frames = frames * wv[:, None]
        spec = jnp.fft.rfft(frames, axis=-2) if onesided \
            else jnp.fft.fft(frames, axis=-2)
        if normalized:
            spec = spec / math.sqrt(n_fft)
        return spec
    return apply_op(fn, x)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wv = window.value if isinstance(window, Tensor) else (
        jnp.asarray(window) if window is not None
        else jnp.ones(win_length))
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        wv = jnp.pad(wv, (pad, n_fft - win_length - pad))

    def fn(spec):
        if normalized:
            spec = spec * math.sqrt(n_fft)
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-2) if onesided \
            else jnp.fft.ifft(spec, axis=-2).real
        frames = frames * wv[:, None]
        num = frames.shape[-1]
        n = (num - 1) * hop_length + n_fft
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        wsum = jnp.zeros(n, frames.dtype)
        for i in range(num):
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[..., sl].add(frames[..., :, i])
            wsum = wsum.at[sl].add(wv * wv)
        out = out / jnp.maximum(wsum, 1e-8)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out
    return apply_op(fn, x)
