from .sharding_stage import (ShardingOptimizerStage2, ShardingStage2,
                             ShardingStage3, GroupShardedOptimizerStage2,
                             GroupShardedStage2, GroupShardedStage3)
