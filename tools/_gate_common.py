#!/usr/bin/env python
"""Shared plumbing for the compile-observatory ratchet gates
(tools/check_compile_budget.py, tools/check_fusion.py) — and the
canonical workload that produces their ledger.

The gates compare per-executable `kind:"compile"` records (the
compilation observatory's ledger, profiler/compile_observatory.py)
against the checked-in BASELINE_HLO.json. The ledger can come from any
metrics JSONL (`--ledger file.jsonl`), but the apples-to-apples source
is the CANONICAL WORKLOAD here: a fixed tiny GPT train step (per-step,
scanned run_steps, scanned accumulate), a two-bucket serving engine,
the ragged paged-attention serving step (serve.ragged_step: the
Pallas mixed prefill+decode program behind GenerationEngine), a
2-engine DISAGGREGATED ServingRouter (prefill/decode roles over one
shared page pool — the router tier adds zero executables and lands
real kind:"route" records in the tier-1-linted ledger), a
SPECULATIVE engine (1-layer draft, k=2 — the verify rows pad into the
warmed decode signature, so speculation too must add zero target
executables AND zero steady-state draft traces), and an SSM engine
(models/ssm.py over a RecurrentStateCache — the second model family's
O(1) cache strategy: same ragged tag, its own exec signature, serve
records stamped cache_strategy="recurrent"),
compiled cold (persistent cache off) on the single-device CPU backend —
same model, same shapes, same flags every run, so fusion counts and
bytes-accessed are deterministic and compile seconds are comparable.

    python tools/_gate_common.py --emit OUT.jsonl   # run the workload
                                                    # (in a clean child
                                                    # env — the gates
                                                    # spawn this)

The workload WARMS its executables through the background compile
pipeline (jit/warm.py: train.step / run_steps / accumulate and both
serving buckets lower+compile concurrently), then runs the steady-state
calls — which must add ZERO executables beyond the warmed set (the
executable-sharing warmup contract; the emit fails loudly otherwise).
The warm set's `kind:"warm"` record carries wall_s next to the sum of
per-executable seconds — the overlap evidence check_compile_budget.py
ratchets as the `warm_set` comparand.

BASELINE_HLO.json schema (v1):

    {"schema": "paddle_tpu.hlo_baseline.v1",
     "executables": {"<tag>": {"lower_s": .., "compile_s": ..,
                               "total_s": .., "fusion_count": N,
                               "bytes_accessed": B, "instructions": M,
                               "flops": F}, ...},
     "warm_set": {"wall_s": .., "sum_s": .., "n_executables": N}}

Ratcheting: the gates never loosen the baseline; `--update` rewrites an
entry only when the current run is BETTER (lower seconds / fewer
fusions / fewer bytes), so the checked-in numbers always record the
best this container has done — regressions compare against that.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_DEFAULT = os.path.join(REPO, "BASELINE_HLO.json")
BASELINE_SCHEMA = "paddle_tpu.hlo_baseline.v1"


class GateError(Exception):
    """A gate could not even produce numbers (workload crash, bad
    baseline) — distinct from a regression verdict."""


def load_baseline(path):
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict) or \
            not isinstance(payload.get("executables"), dict):
        raise GateError(f"{path}: not a {BASELINE_SCHEMA} baseline "
                        "(no 'executables' table)")
    return payload


def save_baseline(path, payload):
    import time
    payload["schema"] = BASELINE_SCHEMA
    payload["recorded_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime())
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def _load_kind(path, kind):
    recs = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise GateError(f"{path}:{lineno}: not JSONL ({e})")
            if isinstance(rec, dict) and rec.get("kind") == kind:
                recs.append(rec)
    return recs


def load_compile_records(path):
    """The `kind:"compile"` records of one metrics JSONL file."""
    return _load_kind(path, "compile")


def load_warm_record(path):
    """The LAST `kind:"warm"` record of one metrics JSONL file (the
    warm-set wall-vs-sum evidence jit/warm.join exports), or None when
    the ledger carries none — a pre-warm-pipeline ledger stays a valid
    gate source for the per-executable comparisons."""
    recs = _load_kind(path, "warm")
    return recs[-1] if recs else None


def aggregate(records):
    """Per-tag rollup for the gates (plain JSON math, no framework
    import: a gate given --ledger must stay a milliseconds-fast diff).
    Unlike profiler/compile_observatory.aggregate (which SUMS seconds
    for attribution), the gate comparand is the tag's single SLOWEST
    compile — `lower_s`/`compile_s`/`total_s` are the components of
    that one record. A real run's ledger legitimately carries several
    signatures per tag (tail batch, eval dtype); N ordinary compiles
    must not add up to a fake budget regression, while one genuinely
    slow compile still trips it. Max fusion/bytes/instructions across
    signatures, cache_hit only when every compile hit."""
    out = {}
    for r in records:
        t = out.setdefault(r.get("tag", "?"), {
            "lower_s": 0.0, "compile_s": 0.0, "total_s": 0.0,
            "cache_hit": True, "signatures": 0, "fusion_count": 0,
            "bytes_accessed": 0.0, "instructions": 0, "flops": 0.0})
        lower = float(r.get("lower_s", 0.0))
        comp = float(r.get("compile_s", 0.0))
        if lower + comp >= t["total_s"]:
            t["lower_s"], t["compile_s"] = lower, comp
            t["total_s"] = lower + comp
        t["cache_hit"] = t["cache_hit"] and bool(r.get("cache_hit"))
        t["signatures"] += 1
        t["fusion_count"] = max(t["fusion_count"],
                                int(r.get("fusion_count", 0)))
        t["bytes_accessed"] = max(t["bytes_accessed"],
                                  float(r.get("bytes_accessed", 0.0)))
        t["instructions"] = max(t["instructions"],
                                int(r.get("instructions", 0)))
        t["flops"] = max(t["flops"], float(r.get("flops", 0.0)))
    return out


def run_workload(out_path, timeout=300):
    """Run the canonical workload in a CLEAN subprocess (CPU backend,
    single device, persistent cache off, metrics JSONL -> out_path) and
    return its aggregated per-tag ledger."""
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PADDLE_TPU_COMPILE_CACHE": "0",
        "PADDLE_TPU_METRICS_FILE": str(out_path),
        "PYTHONUNBUFFERED": "1",
        # the child is `python tools/_gate_common.py`, whose sys.path[0]
        # is tools/ — the repo root must be importable for paddle_tpu
        "PYTHONPATH": REPO + (os.pathsep + env["PYTHONPATH"]
                              if env.get("PYTHONPATH") else ""),
    })
    env.pop("PADDLE_TPU_DEBUG_DUMP", None)
    # determinism: one host device, whatever the parent (e.g. the
    # 8-device test harness) had configured
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "--xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=1"]).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--emit",
             str(out_path)],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired as e:
        # infrastructure failure (exit 2), NOT a budget verdict (exit
        # 1): a wedged workload must not read as a named regression
        raise GateError(
            f"canonical workload hung past {timeout}s "
            f"(stderr tail: {(e.stderr or b'')[-500:]!r})") from None
    if proc.returncode != 0:
        raise GateError("canonical workload failed "
                        f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}")
    return aggregate(load_compile_records(out_path))


def emit_workload():
    """The canonical workload body (runs in the child run_workload
    spawns; expects the env above to be set already).

    The full warm set — the three TrainStep program flavors, both
    serving buckets, and the ragged serving step's prefill+decode
    signatures — compiles OVERLAPPED through the background
    compile pipeline (jit/warm.py), exactly as a production startup
    would; `jit.warm.join` exports the `kind:"warm"` wall-vs-sum
    record the compile-budget gate ratchets. The steady-state calls
    then run against the warmed executables and must add ZERO compile
    records (the executable-sharing warmup contract) — violating that
    fails the emit, and therefore both gates, loudly."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit import TrainStep, warm as jwarm
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
    from paddle_tpu.profiler import compile_observatory as cobs

    paddle.seed(0)
    # scan_layers=True (the GPTConfig default) is deliberate: compile-
    # bound paths lower ONE block body, not num_layers of them
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=16, dropout=0.0)
    model = GPTForCausalLM(cfg)
    o = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    def loss_fn(logits, labels):
        V = logits.shape[-1]
        return nn.functional.cross_entropy(
            logits.reshape([-1, V]), labels.reshape([-1]))

    step = TrainStep(model, loss_fn, o)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, size=(2, 16)).astype(np.int32))
    stacked = paddle.to_tensor(
        np.stack([ids.numpy(), ids.numpy()]))

    from paddle_tpu.inference import (InferenceEngine, GenerationEngine,
                                      ServingRouter)
    paddle.seed(0)
    eng = InferenceEngine(nn.Linear(8, 8), batch_sizes=(1, 2),
                          name="canonical")
    x_serve = np.zeros((1, 8), np.float32)
    # the ragged serving executable (serve.ragged_step — the Pallas
    # mixed prefill+decode program): its own tiny GPT in eval mode so
    # the train step's donation traffic can't touch its param snapshot.
    # prompt 4 + max_new 3 at page_size 16 keeps the table width at 1,
    # and the MIN_Q_TOKENS=8 token-bucket floor (q-blocks must reach
    # the MXU's 8-row sublane tile) collapses the prefill chunk (T=4)
    # and the decode step (T=1) onto ONE signature: (8, 1, 1)
    paddle.seed(0)
    gen_model = GPTForCausalLM(cfg)
    gen_model.eval()
    gen = GenerationEngine(gen_model, n_pages=8, page_size=16,
                           max_batch=2, max_new_tokens=3,
                           name="canonical_gen")
    # the serving FRONT DOOR: a 2-engine disaggregated router (prefill
    # role -> decode role over ONE shared page pool) on the same model
    # and pool geometry as canonical_gen, so every ragged signature it
    # dispatches is already in the warm set — the router tier must add
    # ZERO executables, and tier-1 lints real kind:"route" records
    router = ServingRouter.disaggregated(
        gen_model, n_pages=8, page_size=16, max_batch=2,
        max_new_tokens=3, name="canonical_router")
    # SPECULATIVE decoding through the same ragged step
    # (inference/speculative.py): a 1-layer draft proposes k=2 tokens
    # and the target verifies them as one k+1-token row — which pads
    # into the SAME (8, 1, 1) signature as every other row above, so
    # the speculative engine must add ZERO target executables, and its
    # draft's own schedule compiles entirely inside the warm set
    from paddle_tpu.inference import SpeculativeConfig
    paddle.seed(1)
    draft_cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                          num_heads=2, max_position_embeddings=16,
                          dropout=0.0)
    draft_model = GPTForCausalLM(draft_cfg)
    draft_model.eval()
    spec = GenerationEngine(gen_model, n_pages=8, page_size=16,
                            max_batch=2, max_new_tokens=3,
                            name="canonical_spec",
                            speculative=SpeculativeConfig(draft_model,
                                                          k=2))
    # the SECOND MODEL FAMILY (models/ssm.py): an O(1)-cache SSM engine
    # through the SAME serve.ragged_step tag — its RecurrentStateCache
    # keys a distinct executable via cache.exec_signature(), warmed
    # here like every other signature, and its serve/request/kvcache
    # records stamp cache_strategy="recurrent" so tier-1 lints the
    # strategy-conditional schema rules against real records
    from paddle_tpu.models.ssm import SSMConfig, SSMForCausalLM
    paddle.seed(2)
    ssm_cfg = SSMConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        d_state=8, d_conv=4, expand=2,
                        max_position_embeddings=16)
    ssm_model = SSMForCausalLM(ssm_cfg)
    ssm_model.eval()
    ssm = GenerationEngine(ssm_model, n_pages=8, page_size=16,
                           max_batch=2, max_new_tokens=3,
                           name="canonical_ssm")
    handles = [
        step.warm(ids, ids),                       # train.step
        step.warm_run_steps(2, ids, ids),          # train.run_steps
        step.warm_accumulate(2, stacked, stacked),  # train.accumulate
    ] + eng.warm_async(x_serve) \
      + gen.warm_async(4, 3) \
      + router.warm_async(4, 3) \
      + spec.warm_async(4, 3) \
      + ssm.warm_async(4, 3)                       # serve.ragged_step
    summary = jwarm.join(handles)                  # kind:"warm" record
    warmed = cobs.ledger_signatures()
    # the draft shares the target's RAGGED_TAG, so the ledger-pair
    # check alone cannot see a steady-state DRAFT compile — the
    # per-model trace counters can, and must not move either
    traces0 = getattr(gen_model, "_ragged_traces", 0) \
        + getattr(draft_model, "_ragged_traces", 0) \
        + getattr(ssm_model, "_ragged_traces", 0)

    # steady state over the warmed executables
    float(step(ids, ids).item())
    step.run_steps(2, ids, ids)
    float(step.accumulate(2, stacked, stacked).item())
    eng(x_serve)
    eng.shutdown()
    gen.submit(np.array([1, 2, 3, 4]), max_new_tokens=3).result(120)
    gen.shutdown()
    spec.submit(np.array([1, 2, 3, 4]), max_new_tokens=3).result(120)
    spec.shutdown()
    ssm.submit(np.array([1, 2, 3, 4]), max_new_tokens=3).result(120)
    ssm.shutdown()
    router.submit(np.array([1, 2, 3, 4]), max_new_tokens=3,
                  deadline_ms=120_000).result(120)
    router._fleet_mon.snapshot()  # force ONE kind:"fleet" record: the
    router.shutdown()             # cadence (5 s) never fires in-gate
    steady = cobs.ledger_signatures()
    if steady != warmed:
        raise AssertionError(
            "executable-sharing warmup contract violated: steady state "
            f"compiled {sorted(steady - warmed)} beyond the warmed set "
            f"(warm summary: {summary})")
    traces1 = getattr(gen_model, "_ragged_traces", 0) \
        + getattr(draft_model, "_ragged_traces", 0) \
        + getattr(ssm_model, "_ragged_traces", 0)
    if traces1 != traces0:
        raise AssertionError(
            "speculative steady state retraced the ragged step "
            f"({traces0} -> {traces1} model-level traces) — the draft "
            "schedule or the verify-row bucketing missed a signature")

    # the serving observatory contract: every request submitted to
    # either engine lands EXACTLY ONE schema-valid kind:"request"
    # record whose token counts reconcile with the engine counters,
    # and the generation engine snapshots its page pool
    # (kind:"kvcache") — in the same tier-1-exercised ledger the
    # compile gates read, so the lint sees real instances
    import json as _json
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import check_metrics_schema as _cms
    from paddle_tpu.profiler import monitor as _pmon
    mfile = os.environ["PADDLE_TPU_METRICS_FILE"]
    reqs = _load_kind(mfile, "request")
    kvs = _load_kind(mfile, "kvcache")
    routes = _load_kind(mfile, "route")
    schema_errs = [e for r in reqs + kvs + routes
                   for e in _cms.validate_line(_json.dumps(r))]
    if schema_errs:
        raise AssertionError(
            f"serving observatory records violate the schema: "
            f"{schema_errs[:5]}")
    by_engine = {}
    for r in reqs:
        by_engine.setdefault(r["engine"], []).append(r)
    # the router request's trace is born at the PREFILL engine's submit
    # and SPLITS at the handoff: the prefill half closes with outcome
    # "handoff", the decode half carries the request to its terminal —
    # four records, one per engine, same request_id on the router pair
    if sorted(by_engine) != ["canonical", "canonical_gen",
                             "canonical_router_decode",
                             "canonical_router_prefill",
                             "canonical_spec", "canonical_ssm"] or \
            any(len(v) != 1 for v in by_engine.values()):
        raise AssertionError(
            "expected exactly one request record per engine "
            f"(prefill+decode halves split), got "
            f"{[(k, len(v)) for k, v in sorted(by_engine.items())]}")
    pre_rec = by_engine["canonical_router_prefill"][0]
    dec_rec = by_engine["canonical_router_decode"][0]
    if pre_rec["outcome"] != "handoff" or \
            pre_rec.get("handoff_of") != "canonical_router_decode" or \
            dec_rec.get("handoff_of") != "canonical_router_prefill" or \
            pre_rec["request_id"] != dec_rec["request_id"]:
        raise AssertionError(
            "the disaggregated pair must cross-name each other via "
            "handoff_of under ONE request_id: "
            f"prefill {pre_rec}, decode {dec_rec}")
    if any(r["outcome"] != "completed" for r in reqs
           if r["outcome"] != "handoff"):
        raise AssertionError(
            f"canonical requests must complete, got "
            f"{[(r['engine'], r['outcome']) for r in reqs]}")
    gen_total = _pmon.get_metric("serve.generated_tokens")
    gen_total = int(gen_total.value) if gen_total else 0
    # terminal records only: the handoff half's tokens are re-counted
    # by the decode half (seeded at adoption)
    rec_total = sum(r["generated_tokens"] for r in reqs
                    if r["outcome"] == "completed")
    if rec_total != gen_total or rec_total != 12:  # 4 x max_new_tokens=3
        raise AssertionError(
            "request-record token counts do not reconcile with the "
            f"engine counters: records {rec_total}, "
            f"serve.generated_tokens {gen_total}, expected 12")
    # the speculative contract: the canonical_spec request carries the
    # schema-valid proposed/accepted trio with real proposals, every
    # NON-speculative record stamps zeros, and >= 1 kind:"serve" step
    # record from canonical_spec reports its verify-row verdict — so
    # tier-1 lints real speculative records in the same ledger
    spec_rec = by_engine["canonical_spec"][0]
    if spec_rec.get("proposed_tokens", 0) < 1 or \
            spec_rec["accepted_tokens"] > spec_rec["proposed_tokens"]:
        raise AssertionError(
            "the canonical_spec request must propose >= 1 draft token "
            f"and accept at most what it proposed: {spec_rec}")
    for r in reqs:
        if r["engine"] != "canonical_spec" and (
                r.get("proposed_tokens", 0) != 0
                or r.get("accepted_tokens", 0) != 0
                or r.get("accept_rate", 0.0) != 0.0):
            raise AssertionError(
                "non-speculative request records must stamp zero "
                f"speculative counts: {r['engine']} -> {r}")
    serves = _load_kind(mfile, "serve")
    spec_steps = [r for r in serves if r.get("engine") == "canonical_spec"
                  and r.get("proposed_tokens", 0) >= 1]
    if not spec_steps:
        raise AssertionError(
            "expected >= 1 kind:'serve' record from canonical_spec "
            "with proposed_tokens >= 1 (did the draft propose at all?)")
    # the cache-strategy contract: the SSM engine stamps every serve
    # record with its strategy (and its request/kvcache records with
    # the same — schema-validated above), so tier-1 exercises the
    # strategy-conditional rules against REAL recurrent records
    ssm_steps = [r for r in serves
                 if r.get("engine") == "canonical_ssm"
                 and r.get("cache_strategy") == "recurrent"]
    if not ssm_steps:
        raise AssertionError(
            "expected >= 1 kind:'serve' record from canonical_ssm "
            "stamped cache_strategy='recurrent', got "
            f"{[(r.get('engine'), r.get('cache_strategy')) for r in serves][:8]}")
    if by_engine["canonical_ssm"][0].get("cache_strategy") \
            != "recurrent":
        raise AssertionError(
            "the canonical_ssm request record must stamp its strategy: "
            f"{by_engine['canonical_ssm'][0]}")
    errs = [e for r in serves
            for e in _cms.validate_line(_json.dumps(r))]
    if errs:
        raise AssertionError(
            f"serve records violate the schema: {errs[:5]}")
    if pre_rec["generated_tokens"] != 1:
        raise AssertionError(
            "the prefill half streams exactly its first token before "
            f"handing off, got {pre_rec['generated_tokens']}")
    kv_engines = {r["engine"] for r in kvs}
    if not kvs or "canonical_gen" not in kv_engines:
        raise AssertionError(
            f"expected kind:'kvcache' snapshots from canonical_gen, "
            f"got {[(r.get('engine'), r.get('kind')) for r in kvs][:5]}")
    # the front-door contract: the one router request lands >= 1
    # "dispatched" decision on the prefill engine AND exactly one
    # "handoff" moving its chain to the decode engine with reconciling
    # page counts (the schema cross-checks ceil(tokens/page_size))
    outcomes = {r["outcome"] for r in routes}
    if not {"dispatched", "handoff"} <= outcomes:
        raise AssertionError(
            f"expected dispatched + handoff route records, got "
            f"{[(r.get('outcome'), r.get('engine')) for r in routes]}")
    hoffs = [r for r in routes if r["outcome"] == "handoff"]
    if len(hoffs) != 1 or \
            hoffs[0]["engine"] != "canonical_router_decode" or \
            hoffs[0]["from_engine"] != "canonical_router_prefill" or \
            hoffs[0]["chain_tokens"] != 4:
        raise AssertionError(
            f"handoff record does not match the canonical request: "
            f"{hoffs}")

    # the fleet-observatory contract: the one handed-off request lands
    # EXACTLY ONE schema-valid kind:"journey" record joining the route
    # decision and both request records under one request_id, with the
    # handoff gap MEASURED (export stamp -> adopt stamp, >= 0), and the
    # forced pre-shutdown snapshot emitted >= 1 schema-valid
    # kind:"fleet" record — all in the same ledger the gates read
    journeys = _load_kind(mfile, "journey")
    fleets = _load_kind(mfile, "fleet")
    errs = [e for r in journeys + fleets
            for e in _cms.validate_line(_json.dumps(r))]
    if errs:
        raise AssertionError(
            f"fleet-observatory records violate the schema: {errs[:5]}")
    if len(journeys) != 1:
        raise AssertionError(
            "expected exactly one kind:'journey' record for the one "
            f"handed-off request, got {len(journeys)}")
    j = journeys[0]
    if j["request_id"] != pre_rec["request_id"] or \
            j["request_id"] != hoffs[0].get("request_id") or \
            j["prefill_engine"] != "canonical_router_prefill" or \
            j["decode_engine"] != "canonical_router_decode":
        raise AssertionError(
            "the journey must join the route decision and both request "
            f"records under one request_id: {j}")
    if j["handoff_gap_s"] < 0 or j["outcome"] != "completed" or \
            j["generated_tokens"] != 3 or j["chain_tokens"] != 4:
        raise AssertionError(
            f"journey accounting does not match the canonical "
            f"request: {j}")
    if not fleets or any(r["router"] != "canonical_router"
                         for r in fleets):
        raise AssertionError(
            f"expected >= 1 kind:'fleet' snapshot from "
            f"canonical_router, got {fleets[:3]}")

    # the distributed-observatory contract: the canonical workload must
    # land ≥1 schema-valid kind:"collective" record (an eager
    # all_reduce + wait — the first call per op is always sampled) and
    # ≥1 kind:"rankstat" record (the train steps above emitted one at
    # the first-step cadence) in the same tier-1-exercised ledger, so
    # the lint sees real instances of both new kinds
    import paddle_tpu.distributed as dist
    from paddle_tpu.profiler import dist_observatory as _dobs
    ct = paddle.to_tensor(np.ones(1024, np.float32))
    dist.all_reduce(ct)
    dist.wait(ct)
    rs = _dobs.emit_rankstat(force=True)
    if rs is None:
        raise AssertionError("emit_rankstat produced no record")
    colls = _load_kind(mfile, "collective")
    rstats = _load_kind(mfile, "rankstat")
    if not colls or not rstats:
        raise AssertionError(
            f"expected >=1 kind:'collective' and >=1 kind:'rankstat' "
            f"record, got {len(colls)} / {len(rstats)}")
    errs = [e for r in colls + rstats
            for e in _cms.validate_line(_json.dumps(r))]
    if errs:
        raise AssertionError(
            f"distributed-observatory records violate the schema: "
            f"{errs[:5]}")
    ops = {r["op"] for r in colls}
    if "all_reduce" not in ops:
        raise AssertionError(
            f"expected an all_reduce collective record, got ops {ops}")
    roll = _dobs.collective_rollup()
    if roll.get("all_reduce", {}).get("bytes", 0) < 4096:
        raise AssertionError(
            f"collective rollup did not fold the all_reduce payload: "
            f"{roll}")

    # the fault-tolerance contract: one snapshot-then-write checkpoint
    # save + verified resume on the canonical train step, so tier-1
    # lints REAL kind:"ckpt" records (schema: phases sum <= total,
    # bytes > 0, verified flag) in the same ledger the gates read
    import shutil as _shutil
    import tempfile as _tempfile
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    ck_dir = _tempfile.mkdtemp(prefix="gate_ckpt_")
    try:
        mgr = CheckpointManager(ck_dir, keep_last=2)
        step_before = step._step_i
        handle = mgr.save(step)
        handle.result(120)  # committed
        restored = CheckpointManager(ck_dir).restore(step)
        if restored != step_before:
            raise AssertionError(
                f"checkpoint resume restored step {restored}, expected "
                f"{step_before}")
        ckpts = _load_kind(mfile, "ckpt")
        saves = [r for r in ckpts if r.get("op") == "save"]
        restores = [r for r in ckpts if r.get("op") == "restore"]
        if not saves or not restores:
            raise AssertionError(
                f"expected kind:'ckpt' save+restore records, got "
                f"{[(r.get('op'), r.get('step')) for r in ckpts]}")
        errs = [e for r in ckpts
                for e in _cms.validate_line(_json.dumps(r))]
        if errs:
            raise AssertionError(
                f"ckpt records violate the schema: {errs[:5]}")
        if not saves[-1]["committed"] or not restores[-1]["verified"]:
            raise AssertionError(
                f"canonical checkpoint must commit and verify: "
                f"{saves[-1]}, {restores[-1]}")
        mgr.close()
    finally:
        _shutil.rmtree(ck_dir, ignore_errors=True)

    # the memory-observatory contract: the canonical workload lands
    # schema-valid kind:"memory" records from BOTH the train step
    # cadence (source "train", first step always) and a serving
    # engine's kvcache cadence (source "serve", carrying the pool's
    # occupancy + measured hbm gauges), and the kv-pool TAG's ledger
    # bytes reconcile with pool_stats() page counts x measured
    # per-page bytes to within page granularity — measured
    # attribution, not analytic claims
    mems = _load_kind(mfile, "memory")
    errs = [e for r in mems for e in _cms.validate_line(_json.dumps(r))]
    if errs:
        raise AssertionError(
            f"memory records violate the schema: {errs[:5]}")
    train_mems = [r for r in mems if r.get("source") == "train"]
    serve_mems = [r for r in mems if r.get("source") == "serve"]
    if not train_mems or not serve_mems:
        raise AssertionError(
            "expected >= 1 kind:'memory' record from BOTH the train "
            f"step path and a serving engine, got "
            f"{len(train_mems)} train / {len(serve_mems)} serve")
    if not any("params" in r.get("tags", {}) and
               r["tags"]["params"] > 0 for r in train_mems):
        raise AssertionError(
            "train memory records must attribute the params store "
            f"(tags of the first: {train_mems[0].get('tags')})")
    kv_serve = [r for r in serve_mems
                if "n_pages" in r and "page_bytes" in r
                and f"kv_pool.{r.get('engine')}" in r.get("tags", {})]
    if not kv_serve:
        raise AssertionError(
            "expected >= 1 serve memory record carrying its kv pool's "
            "n_pages/page_bytes next to the kv_pool tag, got "
            f"{[(r.get('engine'), sorted(r.get('tags', {}))) for r in serve_mems][:4]}")
    for r in kv_serve:
        tag_b = r["tags"][f"kv_pool.{r['engine']}"]
        pool_b = r["n_pages"] * r["page_bytes"]
        if abs(tag_b - pool_b) > r["page_bytes"]:
            raise AssertionError(
                "kv-pool ledger bytes do not reconcile with "
                f"pool_stats page math on {r['engine']}: tag "
                f"{tag_b} vs n_pages {r['n_pages']} x page_bytes "
                f"{r['page_bytes']} = {pool_b}")

    # the static-analysis contract: the canonical workload runs
    # paddlelint (tools/paddlelint.py — docs/STATIC_ANALYSIS.md) over
    # the repo and lands its findings as `kind:"lint"` records in the
    # same tier-1-exercised ledger the gates read. The repo must be
    # CLEAN (zero unsuppressed findings) and the ledger must carry >=1
    # schema-valid lint record (the suppressed findings with their
    # reasons — an empty lint section would mean the linter silently
    # stopped looking)
    import paddlelint as _plint
    lint_findings, _ = _plint.run_passes(REPO)
    unsup = [f for f in lint_findings if not f.suppressed]
    if unsup:
        raise AssertionError(
            f"paddlelint found {len(unsup)} unsuppressed finding(s) "
            f"at HEAD; first: {unsup[0].render()}")
    for lrec in _plint.records(lint_findings):
        _pmon.export_step(
            {k: v for k, v in lrec.items()
             if k not in ("ts", "rank", "kind")}, kind="lint")
    lints = _load_kind(mfile, "lint")
    if not lints:
        raise AssertionError(
            "expected >=1 kind:'lint' record in the canonical ledger "
            "(paddlelint emitted none — did the fileset walk break?)")
    errs = [e for r in lints for e in _cms.validate_line(_json.dumps(r))]
    if errs:
        raise AssertionError(
            f"lint records violate the schema: {errs[:5]}")
    if not any(r.get("suppressed") and r.get("reason") for r in lints):
        raise AssertionError(
            "expected at least one suppressed lint finding carrying "
            "its reason (the hot-sync allowlist alone guarantees "
            "several at HEAD)")


def format_row(tag, parts):
    return f"  {tag:<28} " + "  ".join(parts)


def main(argv):
    if argv[:1] == ["--emit"]:
        out = argv[1] if len(argv) > 1 else None
        if out and not os.environ.get("PADDLE_TPU_METRICS_FILE"):
            os.environ["PADDLE_TPU_METRICS_FILE"] = out
        emit_workload()
        n = len(load_compile_records(
            os.environ["PADDLE_TPU_METRICS_FILE"]))
        print(f"canonical workload: {n} compile records -> "
              f"{os.environ['PADDLE_TPU_METRICS_FILE']}", file=sys.stderr)
        return 0
    print(__doc__)
    return 2


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
