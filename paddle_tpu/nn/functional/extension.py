"""Extension functionals. Parity: python/paddle/nn/functional/extension.py."""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...framework.dtype import convert_dtype
    dt = convert_dtype(dtype)
    if maxlen is None:
        maxlen = int(x.numpy().max())
    ml = int(maxlen.item()) if isinstance(maxlen, Tensor) else int(maxlen)

    def fn(lens):
        r = jnp.arange(ml)
        return (r[None, :] < lens[..., None]).astype(dt)
    return apply_op(fn, x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    def fn(a):
        if data_format == "NHWC":
            a = jnp.moveaxis(a, -1, 1)
        NT, C, H, W = a.shape
        N = NT // seg_num
        v = a.reshape(N, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        back = jnp.concatenate(
            [v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], axis=1)
        keep = v[:, :, c2:]
        out = jnp.concatenate([back, fwd, keep], axis=2)
        out = out.reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op(fn, x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    from ...tensor.creation import diag_embed as de
    return de(x, offset, dim1, dim2)
