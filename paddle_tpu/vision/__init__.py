"""paddle.vision. Parity: python/paddle/vision/__init__.py."""
from . import models
from . import transforms
from . import datasets
from . import ops
from .models import *  # noqa: F401,F403

image_backend = "cv2"


def set_image_backend(backend):
    global image_backend
    image_backend = backend


def get_image_backend():
    return image_backend


def image_load(path, backend=None):
    """Parity: paddle.vision.image_load."""
    from .datasets import _load_image
    return _load_image(path)
