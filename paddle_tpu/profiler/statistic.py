"""Host-side span/event statistics — the in-process half of the profiler.

Parity: python/paddle/profiler/profiler_statistic.py (the RecordEvent
summary tables). The reference aggregates C++ HostTraceLevel events into
nested per-name tables; here `RecordEvent` (and every instrumented
framework hot path — jit compile, train step, DataLoader, collectives,
memory queries) reports into this module's in-process recorder, and
`Profiler.summary()` renders the aggregated table. The device-side story
stays with jax.profiler (XLA op timelines in TensorBoard/Perfetto); this
module is the always-on, zero-dependency host view.

Spans nest: a span that begins while another is open on the same thread
becomes its child, and the summary table indents children under their
parent with per-node call counts, total/avg/max wall time, and the share
of all recorded top-level time. Threads merge into one tree (a node
remembers which threads hit it); `thread_sep=True` renders one tree per
thread.
"""
import threading
import time

from . import flight_recorder

__all__ = ["SpanNode", "span", "begin_span", "end_span", "record_span",
           "reset_statistics", "snapshot", "summary_table", "get_events",
           "SortedKeys"]


class SortedKeys:
    """Parity: paddle.profiler.SortedKeys (subset: host-side orders)."""
    CPUTotal = "total"
    CPUAvg = "avg"
    CPUMax = "max"
    Calls = "calls"


class SpanNode:
    """One aggregated named span at one position in the nesting tree."""
    __slots__ = ("name", "count", "total", "max", "min", "threads",
                 "children")

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")
        self.threads = set()
        self.children = {}

    def add(self, seconds, thread_ident):
        self.count += 1
        self.total += seconds
        if seconds > self.max:
            self.max = seconds
        if seconds < self.min:
            self.min = seconds
        self.threads.add(thread_ident)

    def child(self, name):
        node = self.children.get(name)
        if node is None:
            node = SpanNode(name)
            self.children[name] = node
        return node

    def to_dict(self):
        return {"name": self.name, "count": self.count,
                "total_s": self.total, "max_s": self.max,
                "min_s": self.min if self.count else 0.0,
                "avg_s": self.total / self.count if self.count else 0.0,
                "threads": sorted(self.threads),
                "children": [c.to_dict()
                             for c in self.children.values()]}


_lock = threading.RLock()
_root = SpanNode("<root>")
_tls = threading.local()


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def begin_span(name):
    """Open a span on this thread; nested begins become children."""
    _stack().append((name, time.perf_counter()))


def end_span():
    """Close the innermost open span on this thread and record it."""
    st = _stack()
    if not st:
        return 0.0
    name, t0 = st.pop()
    dt = time.perf_counter() - t0
    _record(name, dt, [n for n, _ in st], t0)
    return dt


def record_span(name, seconds):
    """Record an already-measured duration as a span nested under this
    thread's currently-open spans (used by instrumentation that times a
    region itself, e.g. the DataLoader batch wait)."""
    seconds = float(seconds)
    _record(name, seconds, [n for n, _ in _stack()],
            time.perf_counter() - seconds)


def _record(name, seconds, parent_names, t0=None):
    ident = threading.get_ident()
    with _lock:
        node = _root
        for p in parent_names:
            node = node.child(p)
        node.child(name).add(seconds, ident)
    # raw event tail for the timeline view (trace_export.py): the
    # aggregation above answers "how much", the flight-recorder ring
    # answers "when" — a bounded deque append, negligible per span
    flight_recorder.record_span_event(
        name, t0 if t0 is not None else time.perf_counter() - seconds,
        seconds, ident, len(parent_names))


class span:
    """Context manager: `with statistic.span("phase"): ...`"""

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        begin_span(self.name)
        return self

    def __exit__(self, *exc):
        end_span()
        return False


def reset_statistics():
    """Drop all aggregated spans (open spans keep timing and will record
    into the fresh tree when they close)."""
    global _root
    with _lock:
        _root = SpanNode("<root>")


def snapshot():
    """The aggregated span tree as plain dicts (JSON-serializable)."""
    with _lock:
        return [c.to_dict() for c in _root.children.values()]


def get_events(name=None):
    """Flat list of aggregated span records ({path, name, count, total_s,
    avg_s, max_s}); filtered to `name` when given. The queryable form
    load_profiler_result also returns."""
    return flatten(snapshot(), name)


def flatten(tree, name=None, _prefix=""):
    out = []
    for node in tree:
        path = f"{_prefix}/{node['name']}" if _prefix else node["name"]
        rec = {k: node[k] for k in ("name", "count", "total_s", "avg_s",
                                    "max_s", "min_s")}
        rec["path"] = path
        if name is None or node["name"] == name:
            out.append(rec)
        out.extend(flatten(node["children"], name, path))
    return out


_UNIT = {"s": 1.0, "ms": 1e3, "us": 1e6}


def _sort_key(sorted_by):
    return {"total": lambda n: n["total_s"],
            "avg": lambda n: n["avg_s"],
            "max": lambda n: n["max_s"],
            "calls": lambda n: n["count"]}.get(sorted_by or "total",
                                               lambda n: n["total_s"])


def summary_table(sorted_by="total", time_unit="ms", thread_sep=False):
    """Render the aggregated host-span table (parity: the reference's
    profiler_statistic summary). Children indent under their parent;
    Ratio is each node's share of the summed top-level wall time."""
    tree = snapshot()
    if not tree:
        return "no host spans recorded"
    scale = _UNIT.get(time_unit, 1e3)
    unit = time_unit if time_unit in _UNIT else "ms"
    grand = sum(n["total_s"] for n in tree) or 1.0
    widths = (44, 8, 12, 12, 12, 8)
    header = ("Name", "Calls", f"Total({unit})", f"Avg({unit})",
              f"Max({unit})", "Ratio")
    sep = "  ".join("-" * w for w in widths)

    def fmt_row(cols):
        name, rest = cols[0], cols[1:]
        cells = [name[:widths[0]].ljust(widths[0])]
        cells += [str(c).rjust(w) for c, w in zip(rest, widths[1:])]
        return "  ".join(cells)

    lines = [sep, fmt_row(header), sep]
    key = _sort_key(sorted_by)

    def emit(nodes, depth):
        for n in sorted(nodes, key=key, reverse=True):
            lines.append(fmt_row((
                "  " * depth + n["name"], n["count"],
                f"{n['total_s'] * scale:.3f}",
                f"{n['avg_s'] * scale:.3f}",
                f"{n['max_s'] * scale:.3f}",
                f"{n['total_s'] / grand * 100:.1f}%")))
            emit(n["children"], depth + 1)

    # thread_sep: the recorder aggregates threads in place (a node keeps
    # the set of thread idents that hit it); exact per-thread splits
    # would need raw event retention, so the merged view is rendered
    # either way and `snapshot()` carries the thread sets.
    emit(tree, 0)
    lines.append(sep)
    return "\n".join(lines)
