"""LeNet / AlexNet / VGG / SqueezeNet. Parity:
python/paddle/vision/models/{lenet,alexnet,vgg,squeezenet}.py."""
from ... import nn
from ...tensor.manipulation import flatten, concat

__all__ = ["LeNet", "AlexNet", "alexnet", "VGG", "vgg11", "vgg13", "vgg16",
           "vgg19", "SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84),
                nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(0.5), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(flatten(x, 1))


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512,
          512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg_features(cfg, batch_norm=False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 49, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def _vgg(cfg, batch_norm=False, pretrained=False, **kwargs):
    return VGG(_vgg_features(_VGG_CFGS[cfg], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("A", batch_norm, pretrained, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("B", batch_norm, pretrained, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("D", batch_norm, pretrained, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("E", batch_norm, pretrained, **kwargs)


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze, 1)
        self.relu = nn.ReLU()
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1(x)),
                       self.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        x = self.classifier(self.features(x))
        return flatten(x, 1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)
