"""Ring attention: sequence-parallel exact attention over the 'sp' axis.

Long-context design (SURVEY.md §6): the sequence dimension is sharded
across devices; each device keeps its Q shard resident and the K/V shards
rotate around the ring via lax.ppermute, one hop per step. Per-hop partial
attention results are merged with the online-softmax rule using each hop's
logsumexp — numerically identical to full attention while never
materializing more than one K/V shard per device. Compute per hop uses the
Pallas flash kernel on TPU (or the reference composition in tests).

Causality over a ring: the KV shard visiting at hop h originates from
device (my_idx - h) mod n. A query block attends to it fully when the
source index is smaller, causally when equal, not at all when larger.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_arrays"]


def _chunk_attn(q, k, v, scale, mode):
    """Partial attention of q vs one kv chunk → (out, lse).
    q,k,v: [B, T, H, D]; mode: 0=skip, 1=causal, 2=full (traced scalar)."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # B,H,Tq,D
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    Tq, Tk = s.shape[-2], s.shape[-1]
    causal_mask = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
    allow = jnp.where(mode == 1, causal_mask,
                      jnp.full((Tq, Tk), True))
    allow = allow & (mode != 0)
    s = jnp.where(allow, s, -1e30)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    # fully-masked rows → lse=-inf, out=0
    out = out / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2), lse  # [B,Tq,H,D], [B,H,Tq]


def ring_attention_arrays(q, k, v, mesh, axis="sp", causal=True,
                          scale=None):
    """q,k,v: [B, T_global, H, D] arrays sharded over `axis` on dim 1.
    Returns attention output with the same sharding."""
    D = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    n = mesh.shape[axis]

    def spmd(q_loc, k_loc, v_loc):
        my = lax.axis_index(axis)
        perm = [(i, (i + 1) % n) for i in range(n)]

        # unrolled loop over ring hops (n is static); per-hop partial
        # results merge afterwards via their logsumexps
        kc, vc = k_loc, v_loc
        outs = []
        lses = []
        for h in range(n):
            src = (my - h) % n
            if causal:
                mode = jnp.where(src == my, 1, jnp.where(src < my, 2, 0))
            else:
                mode = jnp.full((), 2)
            out_h, lse_h = _chunk_attn(q_loc, kc, vc, scale, mode)
            outs.append(out_h)
            lses.append(lse_h)
            if h < n - 1:
                kc = lax.ppermute(kc, axis, perm)
                vc = lax.ppermute(vc, axis, perm)
        lse_stack = jnp.stack(lses)            # [n, B, H, Tq]
        m_all = jnp.max(lse_stack, axis=0)
        w = jnp.exp(lse_stack - m_all[None])   # [n, B, H, Tq]
        w_sum = jnp.sum(w, axis=0)
        out_stack = jnp.stack(outs)            # [n, B, Tq, H, D]
        w_b = jnp.moveaxis(w, 2, 3)[..., None]  # [n, B, Tq, H, 1]
        merged = jnp.sum(out_stack * w_b, axis=0) / jnp.maximum(
            jnp.moveaxis(w_sum, 1, 2)[..., None], 1e-30)
        return merged.astype(q_loc.dtype)

    # batch/head dims ride whatever other mesh axes exist (dp on batch,
    # mp on heads) so the ring composes inside a fleet hybrid step
    # without forcing an all-gather of the dp/mp shards
    def _axis_if(name, dim_size):
        return name if (name in mesh.axis_names
                        and mesh.shape[name] > 1
                        and dim_size % mesh.shape[name] == 0) else None

    b_ax = _axis_if("dp", q.shape[0])
    h_ax = _axis_if("mp", q.shape[2])
    spec = P(b_ax, axis, h_ax, None)
    return shard_map(spmd, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def ring_attention(q, k, v, mesh=None, axis="sp", causal=True, scale=None):
    """Tensor-level entry."""
    from ..framework.core import apply_op
    from ..distributed.env import get_mesh
    mesh = mesh or get_mesh()
    return apply_op(
        lambda qa, ka, va: ring_attention_arrays(qa, ka, va, mesh, axis,
                                                 causal, scale), q, k, v)
