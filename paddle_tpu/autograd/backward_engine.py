"""Reverse-mode engine over the eager tape.

Parity: paddle/fluid/imperative/basic_engine.cc (the dygraph autograd
engine). Design difference: nodes store the *forward* jax function; the VJP
is obtained here with jax.vjp, so backward math is always consistent with
XLA's differentiation rules rather than hand-written grad kernels.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, no_grad
from ..framework.core import _Slot, _Node

__all__ = ["run_backward", "grad"]


def _topo_nodes(root_slots):
    """Topologically order all nodes reachable from the given slots
    (producers before consumers)."""
    order, seen = [], set()
    stack = [(s.node, False) for s in root_slots if s.node is not None]
    while stack:
        node, expanded = stack.pop()
        if node is None:
            continue
        if expanded:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for s in node.in_slots:
            if s.node is not None and id(s.node) not in seen:
                stack.append((s.node, False))
    return order


def _accumulate(slot, g):
    slot.grad = g if slot.grad is None else slot.grad + g


def _backward_pass(root_slots, seed_grads, retain_graph,
                   create_graph=False):
    """Run VJPs in reverse topological order. Returns (all_slots, gslots).

    With create_graph=True every cotangent is itself a taped _Slot (its
    producing _Node holds the VJP function), so the returned gradients are
    differentiable — paddle.grad(create_graph=True) / double-grad parity
    with the reference engine (fluid/imperative/basic_engine.cc +
    dygraph/base.py:grad)."""
    nodes = _topo_nodes(root_slots)
    all_slots = set(root_slots)
    for n in nodes:
        all_slots.update(n.in_slots)
        all_slots.update(n.out_slots)

    # id(slot) -> _Slot carrying that slot's (taped) cotangent
    gslots = {}

    def acc(slot, g_val, g_slot=None):
        if create_graph:
            gs = g_slot if g_slot is not None else _Slot(g_val)
            cur = gslots.get(id(slot))
            if cur is None:
                gslots[id(slot)] = gs
            else:
                ns = _Slot(cur.val + gs.val)
                ns.node = _Node(lambda a, b: a + b, (cur, gs), (ns,),
                                multi=False)
                gslots[id(slot)] = ns
            slot.grad = gslots[id(slot)].val
        else:
            _accumulate(slot, g_val)

    hooked = set()

    def run_hooks(slot):
        """Invoke user hooks once the slot's cotangent is final; a non-None
        return replaces the upstream gradient (ref
        varbase_patch_methods.py:register_hook)."""
        if slot.grad is None or id(slot) in hooked:
            return
        hooked.add(id(slot))
        t = slot.tensor_ref() if slot.tensor_ref else None
        hooks = getattr(t, "_grad_hooks", None) if t is not None else None
        if not hooks:
            return
        if create_graph:
            g = Tensor(gslots[id(slot)])
            g.stop_gradient = False
            for h in hooks:
                r = h(g)
                if r is not None:
                    g = r if isinstance(r, Tensor) else Tensor(r)
            gslots[id(slot)] = g._slot
            slot.grad = g._slot.val
        else:
            with no_grad():
                g = Tensor(slot.grad)
                for h in hooks:
                    r = h(g)
                    if r is not None:
                        g = r if isinstance(r, Tensor) else Tensor(r)
                slot.grad = g.value

    for s, g in zip(root_slots, seed_grads):
        acc(s, g)

    for node in reversed(nodes):
        # reverse-topo order: by now every consumer of node's outputs has
        # contributed its cotangent, so out grads are final -> hooks fire
        for o in node.out_slots:
            run_hooks(o)
        if any(o.grad is not None for o in node.out_slots):
            if hasattr(node, "run_vjp"):  # PyLayer custom backward
                if create_graph:
                    # run the user's backward ON the tape: cotangents are
                    # taped Tensors, the ops inside backward() record
                    # nodes, and the returned grads carry those nodes —
                    # double grad through PyLayer (ref py_layer.py:30)
                    cot_tensors = []
                    for o in node.out_slots:
                        cs = gslots[id(o)] if o.grad is not None \
                            else _Slot(jnp.zeros_like(o.val))
                        t = Tensor(cs)
                        t.stop_gradient = False
                        cot_tensors.append(t)
                    in_grads = node.run_vjp_taped(cot_tensors)
                    for s, g in zip(node.in_slots, in_grads):
                        if g is None:
                            continue
                        if isinstance(g, Tensor):
                            acc(s, g.value, g_slot=g._slot)
                        else:
                            acc(s, g)
                else:
                    with no_grad():
                        cots = tuple(o.grad if o.grad is not None
                                     else jnp.zeros_like(o.val)
                                     for o in node.out_slots)
                        in_cots = node.run_vjp(cots)
                        for s, g in zip(node.in_slots, in_cots):
                            if g is not None:
                                acc(s, g)
            elif create_graph:
                k = len(node.in_slots)
                cot_slots = tuple(
                    gslots[id(o)] if o.grad is not None
                    else _Slot(jnp.zeros_like(o.val))
                    for o in node.out_slots)

                def bw_fn(*vals, _fn=node.fn, _k=k, _multi=node.multi):
                    ins, cots = vals[:_k], vals[_k:]
                    _, vjp = jax.vjp(_fn, *ins)
                    return vjp(tuple(cots) if _multi else cots[0])

                with no_grad():
                    out_grads = bw_fn(*([s.val for s in node.in_slots]
                                        + [cs.val for cs in cot_slots]))
                g_slots = tuple(_Slot(g) for g in out_grads)
                bnode = _Node(bw_fn,
                              tuple(node.in_slots) + cot_slots,
                              g_slots, multi=True)
                for gs in g_slots:
                    gs.node = bnode
                for s, gs in zip(node.in_slots, g_slots):
                    acc(s, gs.val, g_slot=gs)
            else:
                with no_grad():
                    cots = tuple(o.grad if o.grad is not None
                                 else jnp.zeros_like(o.val)
                                 for o in node.out_slots)
                    _, vjp_fn = jax.vjp(node.fn,
                                        *[s.val for s in node.in_slots])
                    in_cots = vjp_fn(cots if node.multi else cots[0])
                    for s, g in zip(node.in_slots, in_cots):
                        if g is not None:
                            acc(s, g)
        # create_graph implies retain: the taped bnodes reference the
        # forward nodes' slots, so freeing them here would silently drop
        # second-order paths through intermediates
        if not retain_graph and not create_graph:
            for o in node.out_slots:
                o.node = None
            node.fn = None
            node.in_slots = ()
    # leaves have no producing node, so their hooks fire here
    for s in all_slots:
        if s.node is None:
            run_hooks(s)
    return all_slots, gslots


def _collect_and_clear(all_slots, into_tensors):
    for s in all_slots:
        if s.grad is None:
            continue
        if into_tensors:
            t = s.tensor_ref() if s.tensor_ref else None
            is_leaf = t is not None and t._slot.node is None
            if t is not None and not t.stop_gradient and (
                    is_leaf or t._retain_grad):
                g = Tensor(s.grad)
                if t.grad is None:
                    t.grad = g
                else:  # Paddle accumulates across backward() calls
                    t.grad = Tensor(t.grad.value + g.value)
        s.grad = None


def run_backward(tensor, grad_tensor=None, retain_graph=False):
    if tensor.stop_gradient:
        raise RuntimeError("backward() on a tensor with stop_gradient=True")
    if grad_tensor is None:
        # reference semantics (varbase_patch_methods.py backward): ANY
        # shape backpropagates with an implicit all-ones cotangent
        seed = jnp.ones_like(tensor.value)
    else:
        seed = grad_tensor.value if isinstance(
            grad_tensor, Tensor) else jnp.asarray(grad_tensor)
    all_slots, _ = _backward_pass([tensor._slot], [seed], retain_graph)
    _collect_and_clear(all_slots, into_tensors=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad parity (python/paddle/fluid/dygraph/base.py:431-466).

    create_graph=True runs the backward itself on the tape (each cotangent
    is a taped slot whose node holds the VJP), so returned grads are
    differentiable — WGAN-GP-style double grad works.
    """
    outputs = list(outputs) if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        seeds = [jnp.ones_like(o.value) for o in outputs]
    else:
        gos = grad_outputs if isinstance(
            grad_outputs, (list, tuple)) else [grad_outputs]
        seeds = [g.value if g is not None else jnp.ones_like(o.value)
                 for o, g in zip(outputs, gos)]

    retain = bool(retain_graph) if retain_graph is not None \
        else bool(create_graph)
    in_slots = [i._slot for i in inputs]
    all_slots, gslots = _backward_pass([o._slot for o in outputs], seeds,
                                       retain, create_graph=create_graph)
    results = []
    for i, s in zip(inputs, in_slots):
        if s.grad is None:
            if not allow_unused:
                raise ValueError(
                    f"an input tensor is unused in the graph "
                    "(pass allow_unused=True)")
            results.append(None)
        elif create_graph:
            g = Tensor(gslots[id(s)])
            g.stop_gradient = False
            results.append(g)
        else:
            results.append(Tensor(s.grad))
    _collect_and_clear(all_slots, into_tensors=False)
    return results
