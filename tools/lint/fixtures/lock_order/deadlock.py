"""Known-bad corpus for the lock-order pass (tests/
test_static_analysis.py runs the pass over this tree and asserts RED).

Two classic shapes: an AB/BA cross-function inversion (two threads
deadlock), and a non-reentrant Lock re-entered through a helper call
(one thread wedges itself)."""
import threading

_a = threading.Lock()
_b = threading.Lock()
_plain = threading.Lock()


def drain_then_export():
    # thread 1 takes a -> b
    with _a:
        with _b:
            pass


def export_then_drain():
    # thread 2 takes b -> a: cycle with drain_then_export
    with _b:
        with _a:
            pass


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats = {}

    def report(self):
        with self._lock:
            return self._summarize()

    def _summarize(self):
        # re-enters the same non-reentrant Lock via the call chain
        with self._lock:
            return dict(self._stats)
