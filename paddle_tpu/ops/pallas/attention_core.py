"""Shared MXU blocking + online-softmax core for the attention kernels.

ONE module owns the block-shape policy and the flash/online-softmax
block update for both Pallas attention kernels (per *Ragged Paged
Attention*, arxiv 2604.15464: the serving and training kernels are the
same blocking with different gather patterns):

- ops/pallas/paged_attention.py — the ragged SERVING kernel: q-blocks
  of mixed prefill+decode tokens (heads folded into the row dimension
  for grouped-query models) against double-buffered kv pages;
- ops/pallas/flash_attention.py — the fused TRAINING kernel: q-blocks
  of one sequence's tokens against contiguous kv blocks, custom VJP.

The policy both enforce: every score dot is [M, D] x [D, Bk] with
M >= MIN_DOT_ROWS (the f32 sublane tile — anything narrower leaves the
128x128 MXU computing mostly zeros; the seed-era serving kernel's
[1, D] x [D, P] per-token dots were the motivating offender), targeting
MXU_ROWS-row tiles when the token count allows.
tools/check_dot_shapes.py ratchets this by parsing the lowered kernels
rather than trusting the claim.

Both kernels run the SAME code in Pallas interpret mode on CPU (tier-1)
— `default_interpret` is the one switch.
"""
import math

import jax
import jax.numpy as jnp

from .common import NEG_INF

# the MXU is a 128x128 systolic array: a score dot wants 128 query rows
MXU_ROWS = 128
# f32 tiles are (8, 128): a dot with M < 8 pads the sublane dimension
# with zeros — the hard floor the dot-shape gate enforces
MIN_DOT_ROWS = 8
# serving pads token counts up to this so q-blocks always reach the
# floor (masked pad rows ride the same MXU tile for free)
MIN_Q_TOKENS = MIN_DOT_ROWS


def choose_q_block(n_tokens, cap=MXU_ROWS):
    """Rows per q-block: the largest divisor of `n_tokens` at most
    `cap`, found by halving (power-of-two token buckets land on `cap`
    exactly; an odd eager-call count runs as one block). Callers with
    folded heads pass cap=MXU_ROWS//fold so M = block * fold still
    targets one MXU tile."""
    bq = max(int(n_tokens), 1)
    cap = max(int(cap), 1)
    while bq > cap and bq % 2 == 0:
        bq //= 2
    return bq


def choose_flash_blocks(t_q, t_k, d):
    """(block_q, block_k) for the training kernel. Biggest blocks win
    decisively on real TPU (measured on [128, 1024, 64] bf16: 1024x1024
    runs fwd 1.9x / fwd+bwd 1.5x faster than 512x512; small bk is the
    worst axis to shrink). 1024x1024 puts the f32 [bq, bk] score+prob
    tiles at ~8 MB of VMEM — about the ceiling once q/k/v/do/acc tiles
    are added, so the cap is the VMEM budget; round down to divisors of
    the seq lens. The dkv backward holds ~3 concurrent f32 [bq, bk]
    tiles plus q/k/v/do tiles that scale with d — shrink bk for head
    dims > 64 to stay inside the same budget the d=64 measurement
    validated. bk seeds at a power of two so the halving loop lands on
    a divisor of a power-of-two t_k instead of collapsing to 1."""
    bq = min(1024, t_q)
    while t_q % bq:
        bq //= 2
    seed = 1024 * 64 // max(d, 64)
    seed = 1 << (seed.bit_length() - 1)
    bk = min(seed, t_k)
    while t_k % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


def default_interpret(interpret):
    """The one interpret-mode switch: None means 'interpret everywhere
    but real TPU' — tier-1 CPU runs execute the identical kernel code
    TPU compiles."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def default_scale(scale, head_dim):
    return 1.0 / math.sqrt(head_dim) if scale is None else float(scale)


def softmax_carry(m_rows, d, dtype=jnp.float32):
    """Fresh (m, l, acc) accumulators for one q-block: running max,
    running sum, unnormalized output — f32 regardless of input dtype."""
    return (jnp.full((m_rows,), NEG_INF, dtype),
            jnp.zeros((m_rows,), dtype),
            jnp.zeros((m_rows, d), dtype))


def softmax_update(m, l, acc, s, v, valid=None):
    """ONE online-softmax block update, shared by both kernels.

    m [M] running max, l [M] running sum, acc [M, D] unnormalized
    accumulator; s [M, Bk] this block's raw scores (pre-mask); v
    [Bk, D] values. `valid` [M, Bk] masks scores out entirely — and,
    unlike plain NEG_INF substitution, zeroes p explicitly, so a row
    with NO valid column in this block (a ragged q-block row whose
    sequence doesn't own the kv page, a causal row above the block
    diagonal) contributes exactly nothing: m stays, alpha = 1, l and
    acc unchanged. NEG_INF is finite (-1e30), so exp never produces
    NaN even for rows nothing has touched yet."""
    if valid is not None:
        s = jnp.where(valid, s, jnp.float32(NEG_INF))
    m_new = jnp.maximum(m, jnp.max(s, axis=1))
    p = jnp.exp(s - m_new[:, None])
    if valid is not None:
        p = jnp.where(valid, p, jnp.float32(0.0))
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=1)
    acc_new = acc * alpha[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def softmax_finalize(m, l, acc):
    """(out [M, D], lse [M]) from the final carry. A row no block ever
    touched (bound-0 pad token) divides 0 by the floor and comes out
    exactly zero — garbage by construction, sliced off by the caller."""
    l_safe = jnp.maximum(l, jnp.float32(1e-30))
    return acc / l_safe[:, None], m + jnp.log(l_safe)


def score_dot(q, k, scale):
    """The score dot both kernels emit: [M, D] x [D, Bk] in f32 on the
    MXU. `k` arrives [Bk, D] (page/block layout); the contraction is
    over D."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return s * jnp.float32(scale)


def causal_valid(iq, ik, block_q, block_k):
    """[block_q, block_k] bool: query row >= kv column (absolute
    positions from the block indices) — the training kernel's mask."""
    rows = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return rows >= cols
