"""Paddle Inference API. Parity: python/paddle/inference/__init__.py +
paddle/fluid/inference/api/ (AnalysisConfig/AnalysisPredictor).

TPU-native: the serialized model is StableHLO (jit.save format); the
Predictor deserializes it into a PjRt executable — XLA replaces the
reference's IR analysis passes and TensorRT engine. Zero-copy handles map
onto device arrays.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType", "DataType", "Tensor", "PredictorPool",
           "get_version", "get_trt_compile_version",
           "get_trt_runtime_version", "get_num_bytes_of_data_type",
           "convert_to_mixed_precision"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class DataType:
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


_DATA_TYPE_BYTES = {DataType.FLOAT32: 4, DataType.INT64: 8,
                    DataType.INT32: 4, DataType.UINT8: 1,
                    DataType.INT8: 1, DataType.FLOAT16: 2,
                    DataType.BFLOAT16: 2}


def get_num_bytes_of_data_type(dtype):
    """Bytes per element of an inference DataType enum value."""
    try:
        return _DATA_TYPE_BYTES[dtype]
    except KeyError:
        raise ValueError(f"unknown inference DataType: {dtype!r}")


def get_version():
    from ..version import full_version
    return f"paddle_tpu inference {full_version} (XLA backend)"


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT on TPU; XLA is the engine


def get_trt_runtime_version():
    return (0, 0, 0)


class PlaceType:
    CPU = 0
    GPU = 1
    TPU = 5


class Config:
    def __init__(self, model_path=None, params_path=None):
        # jit.save writes <prefix>.pdmodel/.pdiparams; accept either the
        # prefix or the explicit .pdmodel path like the reference
        if model_path and model_path.endswith(".pdmodel"):
            model_path = model_path[:-len(".pdmodel")]
        self._prefix = model_path
        self._use_tpu = True
        self._precision = PrecisionType.Float32
        self._enable_memory_optim = True
        self._cpu_math_library_num_threads = 1

    def model_dir(self):
        return os.path.dirname(self._prefix or "")

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return (self._prefix or "") + ".pdiparams"

    # device knobs: XLA owns placement; these record intent for parity
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True

    def enable_tpu(self):
        self._use_tpu = True

    def disable_gpu(self):
        self._use_tpu = False

    def use_gpu(self):
        return self._use_tpu

    def enable_memory_optim(self, x=True):
        self._enable_memory_optim = x

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_library_num_threads = n

    def enable_mkldnn(self):
        pass

    def switch_ir_optim(self, x=True):
        pass  # XLA pipeline always optimizes

    def switch_use_feed_fetch_ops(self, x):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        pass  # XLA is the engine

    def set_precision(self, p):
        self._precision = p

    def summary(self):
        return f"Config(prefix={self._prefix}, tpu={self._use_tpu})"


class _IOHandle:
    """Zero-copy style input/output handle over a device array slot."""

    def __init__(self, predictor, name, is_input):
        self._p = predictor
        self._name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        self._p._inputs[self._name] = jnp.asarray(np.asarray(arr))

    def reshape(self, shape):
        pass

    def copy_to_cpu(self):
        return np.asarray(self._p._outputs[self._name])

    def to_dlpack(self):
        return self._p._outputs[self._name].__dlpack__()

    def shape(self):
        src = self._p._inputs if self._is_input else self._p._outputs
        return list(src[self._name].shape)


class Predictor:
    def __init__(self, config):
        from ..jit import load as jit_load
        self._config = config
        self._layer = jit_load(config._prefix)
        n_in = len(self._layer._meta.get("input_specs", [])) or 1
        self._input_names = [f"input_{i}" for i in range(n_in)]
        self._output_names = []
        self._inputs = {}
        self._outputs = {}

    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        if not self._output_names:
            return ["output_0"]
        return self._output_names

    def get_input_handle(self, name):
        return _IOHandle(self, name, True)

    def get_output_handle(self, name):
        return _IOHandle(self, name, False)

    def run(self, inputs=None):
        if inputs is not None:  # direct list API
            arrs = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
                    for a in inputs]
        else:
            arrs = [self._inputs[n] for n in self._input_names]
        out = self._layer(*arrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"output_{i}" for i in range(len(outs))]
        for n, o in zip(self._output_names, outs):
            self._outputs[n] = o.value if isinstance(o, Tensor) else o
        if inputs is not None:
            return [np.asarray(self._outputs[n])
                    for n in self._output_names]
        return True

    def clone(self):
        return Predictor(self._config)


def create_predictor(config):
    return Predictor(config)


class PredictorPool:
    """`size` independently-cloned Predictors for thread-per-slot
    serving (reference: paddle_inference_api.h services::PredictorPool).
    Each slot has its own io state so threads never share handles."""

    def __init__(self, config, size=1):
        if size < 1:
            raise ValueError("PredictorPool size must be >= 1")
        main = Predictor(config)
        self._preds = [main] + [main.clone() for _ in range(size - 1)]

    def retrive(self, idx):
        return self._preds[idx]

    retrieve = retrive  # the reference spells it "Retrive"; keep both


def convert_to_mixed_precision(*args, **kwargs):
    raise NotImplementedError(
        "convert_to_mixed_precision rewrites a serialized fp32 program; "
        "with paddle_tpu re-export the model under amp instead "
        "(jit.save of a bf16 layer) — see docs/MIGRATION.md")
