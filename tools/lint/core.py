"""paddlelint core: the shared engine every pass builds on.

The framework mechanizes the bug classes the PR 8-12 review-hardening
logs kept finding by hand (blocking file I/O inside an engine lock,
lock-order inversions across threaded modules, unlocked shared-state
snapshots, donated-buffer use-after-dispatch). One driver
(tools/paddlelint.py) runs pluggable passes over a shared project
model; this module owns everything the passes have in common:

- **ProjectContext** — the parsed fileset (one `ast` tree per file),
  the cross-module LOCK REGISTRY (`threading.Lock/RLock/Condition/
  Semaphore` assignments attributed to class fields, so `self._lock`
  in two engines stays two distinct locks), an import-alias map for
  cross-module call resolution, and per-function summaries
  (acquisition sites, call sites with the lexically-held lock set)
  that the interprocedural passes fixpoint over.
- **Suppression engine** — `# lint-ok: <why>` (any pass) and
  `# lint-ok[pass-name]: <why>` (one pass) line markers, same
  discipline as the established `# hot-sync-ok: <why>`: a marker
  WITHOUT a reason is itself a finding (`suppression-needs-reason`),
  never an exemption. Suppressed findings are still emitted
  (`suppressed: true` + the reason) so the JSONL ledger and the
  baseline ratchet see them.
- **Baseline ratchet** — LINT_BASELINE.json records the per-pass
  SUPPRESSED-finding counts. Unsuppressed findings always fail; a
  suppressed count above the baseline fails too (new suppressions
  must be loosened by hand, visibly, in the diff); `--update` only
  ever ratchets counts DOWN, like the HLO gates.

Plain stdlib only — like the other tools/ gates, the linter must run
as a milliseconds-fast source diff with no framework import.

See docs/STATIC_ANALYSIS.md for the pass catalog and how to add one.
"""
import ast
import json
import os
import re
import time

SEVERITIES = ("error", "warning")

# the lint-ok marker: `# lint-ok: why` or `# lint-ok[pass-name]: why`.
# The colon is REQUIRED: without it, `# lint-okay to revisit` or any
# comment merely containing "lint-ok" would count as a reasoned
# suppression with garbage as the recorded reason
LINT_OK_RE = re.compile(
    r"#\s*lint-ok(?:\[(?P<scope>[\w-]+)\])?\s*:\s*(?P<reason>.*)$")
# the hot-sync pass's historical marker (tools/check_no_hot_sync.py);
# the reason discipline (and the colon requirement) applies to it too
HOT_SYNC_OK_RE = re.compile(r"#\s*hot-sync-ok\s*:\s*(?P<reason>.*)$")

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}
REENTRANT_KINDS = {"RLock", "Condition"}  # Condition() wraps an RLock

# receiver-less method names the unique-definition call-resolution
# fallback must NEVER claim: they shadow builtin container / stdlib
# object methods, so `somedict.get(k)` or `somelist.pop()` anywhere in
# the fileset would otherwise resolve to whichever project class
# happens to define the name exactly once
_BUILTIN_METHOD_NAMES = frozenset({
    "get", "pop", "popitem", "clear", "items", "keys", "values",
    "setdefault", "update", "append", "appendleft", "popleft",
    "extend", "insert", "remove", "discard", "add", "sort", "index",
    "count", "copy", "join", "split", "strip", "read", "write",
    "open", "close", "flush", "send", "recv", "put", "start", "run",
    "wait", "result", "submit", "release", "acquire", "notify",
    "notify_all"})


class Finding:
    """One lint finding: pass + rule + file:line + message, plus the
    suppression state the baseline ratchet and the JSONL ledger see."""

    __slots__ = ("pass_name", "rule", "file", "line", "message",
                 "severity", "suppressed", "reason")

    def __init__(self, pass_name, rule, file, line, message,
                 severity="error", suppressed=False, reason=None):
        self.pass_name = pass_name
        self.rule = rule
        self.file = file
        self.line = int(line)
        self.message = message
        self.severity = severity
        self.suppressed = suppressed
        self.reason = reason

    def render(self):
        tail = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return (f"{self.file}:{self.line}: [{self.pass_name}/"
                f"{self.rule}] {self.message}{tail}")

    def record(self, rank=0):
        """The `kind:"lint"` JSONL record (schema:
        tools/check_metrics_schema.py)."""
        rec = {"ts": time.time(), "rank": rank, "kind": "lint",
               "pass": self.pass_name, "rule": self.rule,
               "file": self.file, "line": self.line,
               "severity": self.severity, "message": self.message,
               "suppressed": bool(self.suppressed)}
        if self.suppressed:
            rec["reason"] = self.reason or ""
        return rec


class SourceFile:
    """One parsed source file: text, lines, AST (None when
    unparseable), docstring line mask, and lint-ok markers by line."""

    def __init__(self, root, rel):
        self.root = root
        self.rel = rel
        self.path = os.path.join(root, rel)
        with open(self.path, encoding="utf-8", errors="replace") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.parse_error = None
        try:
            self.tree = ast.parse(self.text)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = str(e)
        # line -> (scope-or-None, reason) of a lint-ok marker
        self.lint_ok = {}
        for i, line in enumerate(self.lines, 1):
            if "lint-ok" in line and "#" in line:
                m = LINT_OK_RE.search(line)
                if m:
                    self.lint_ok[i] = (m.group("scope"),
                                       m.group("reason").strip())

    def string_lines(self):
        """Lines covered by multi-line string constants (docstrings) —
        not code."""
        if self.tree is None:
            return set()
        return string_mask(self.tree)


class FunctionInfo:
    """Per-function summary the interprocedural passes share.

    acquisitions: [(lock_id, line, via_with, has_timeout,
                    held_locks_at_acquisition)]
    calls:        [(callee_key_or_None, held_lock_tuple, line, label)]
    effects:      [(rule, label, line, held_lock_tuple)] — pass-
                  specific direct effects (filled by the blocking
                  pass's extractor)
    """

    __slots__ = ("key", "file", "qualname", "class_name", "node",
                 "acquisitions", "calls", "effects")

    def __init__(self, key, file, qualname, class_name, node):
        self.key = key
        self.file = file
        self.qualname = qualname
        self.class_name = class_name
        self.node = node
        self.acquisitions = []
        self.calls = []
        self.effects = []


def string_mask(tree):
    """Line numbers covered by MULTI-LINE string constants (docstrings
    and block strings) — not code, not linted. The one copy of the
    docstring-mask rule (SourceFile.string_lines and the hot-sync
    pass both use it)."""
    lines = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str):
            end = getattr(node, "end_lineno", node.lineno)
            if end > node.lineno:
                lines.update(range(node.lineno, end + 1))
    return lines


def _acquire_is_bounded(call):
    """True when an `.acquire(...)` call is BOUNDED: a `timeout=`, a
    falsy blocking flag (the non-blocking probe), or a second
    positional (the timeout slot). The first positional/`blocking=`
    is the BLOCKING flag — any truthy constant (`True`, `1`, even a
    float someone mistook for a timeout) is the unbounded wait the
    rule exists to flag. A non-constant flag is treated as bounded
    (unknowable statically; err against false positives)."""
    def negative_const(node):
        # threading defines timeout=-1 as "wait forever": a statically
        # visible negative timeout is the unbounded wait in disguise
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, (int, float)) and \
                not isinstance(node.value, bool):
            return node.value < 0
        if isinstance(node, ast.UnaryOp) and \
                isinstance(node.op, ast.USub) and \
                isinstance(node.operand, ast.Constant):
            return True  # -<literal>
        return False

    # timeout= wins regardless of keyword ORDER: acquire(blocking=True,
    # timeout=2.0) is bounded — unless the timeout is a negative
    # constant (infinite wait)
    for k in call.keywords:
        if k.arg == "timeout":
            return not negative_const(k.value)
    for k in call.keywords:
        if k.arg == "blocking":
            v = k.value
            if isinstance(v, ast.Constant) and v.value:
                return False  # blocking=<truthy>: unbounded
            return True  # blocking=False/0, or a variable
    if len(call.args) >= 2:
        # acquire(blocking, timeout): bounded unless the timeout slot
        # is a negative constant
        return not negative_const(call.args[1])
    if len(call.args) == 1:
        a = call.args[0]
        if isinstance(a, ast.Constant) and a.value:
            return False  # acquire(True)/acquire(1): unbounded
        return True
    return False  # bare acquire()


def _last_attr(node):
    """Trailing attribute/name of a dotted expression, or None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node):
    """Render a Name/Attribute chain as 'a.b.c', or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ProjectContext:
    """The shared project model: fileset + lock registry + function
    index + import aliases. Built once per driver run; passes read it."""

    def __init__(self, root, rels):
        self.root = root
        self.files = []
        for rel in rels:
            try:
                self.files.append(SourceFile(root, rel))
            except OSError:
                continue
        self.locks = {}        # lock_id -> factory kind ("Lock", ...)
        self._attr_locks = set()   # lock ids that are self.<attr> fields
        self._local_locks = set()  # lock ids that are function locals
        self.functions = {}    # "rel:qualname" -> FunctionInfo
        self._module_locks = {}   # rel -> {name} module-level lock names
        self._basenames = {}      # module basename -> [rel]
        self._aliases = {}        # rel -> {alias: basename}
        self._method_defs = {}    # method name -> [function keys]
        self._class_bases = {}    # rel -> {class name: [base names]}
        # build_summaries memo: None = never built, False = built
        # without an extractor, else the extractor it was built with
        self._summaries_extractor = None
        self._build()

    # -- model construction ------------------------------------------

    def _build(self):
        for sf in self.files:
            base = os.path.splitext(os.path.basename(sf.rel))[0]
            if base == "__init__":
                base = os.path.basename(os.path.dirname(sf.rel)) or base
            self._basenames.setdefault(base, []).append(sf.rel)
        for sf in self.files:
            if sf.tree is None:
                continue
            bases = self._class_bases.setdefault(sf.rel, {})
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    bases[node.name] = [b.id for b in node.bases
                                        if isinstance(b, ast.Name)]
        for sf in self.files:
            if sf.tree is None:
                continue
            self._collect_aliases(sf)
            self._collect_locks(sf)
            self._collect_functions(sf)

    def _collect_aliases(self, sf):
        amap = self._aliases.setdefault(sf.rel, {})
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        # `import a.b.c as x`: x IS module c
                        amap[a.asname] = a.name.rsplit(".", 1)[-1]
                    else:
                        # `import a.b.c` binds only the TOP package a
                        top = a.name.split(".")[0]
                        amap[top] = top
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    amap[a.asname or a.name] = a.name

    def _lock_factory(self, call):
        """'Lock'/'RLock'/... when `call` constructs a threading
        primitive, else None."""
        if not isinstance(call, ast.Call):
            return None
        name = _last_attr(call.func)
        return name if name in LOCK_FACTORIES else None

    def _collect_locks(self, sf):
        mod_locks = self._module_locks.setdefault(sf.rel, set())

        def scope_of(stack):
            cls = next((n.name for n in reversed(stack)
                        if isinstance(n, ast.ClassDef)), None)
            fn = next((n.name for n in reversed(stack)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))), None)
            return cls, fn

        def visit(node, stack):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
                kind = self._lock_factory(value)
                pairs = []
                if kind:
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    pairs = [(t, kind) for t in targets]
                elif isinstance(node, ast.Assign) and \
                        isinstance(value, ast.Tuple) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Tuple) and \
                        len(node.targets[0].elts) == len(value.elts):
                    # `lat, lat_lock, errs = [], Lock(), []`
                    pairs = [(t, self._lock_factory(v))
                             for t, v in zip(node.targets[0].elts,
                                             value.elts)
                             if self._lock_factory(v)]
                if pairs:
                    cls, fn = scope_of(stack)
                    for t, k in pairs:
                        lid = self._target_lock_id(sf.rel, t, cls, fn)
                        if lid:
                            self.locks[lid] = k
                            if isinstance(t, ast.Attribute):
                                self._attr_locks.add(lid)
                            elif fn is not None:
                                self._local_locks.add(lid)
                            elif isinstance(t, ast.Name) and not cls:
                                mod_locks.add(t.id)
            for child in ast.iter_child_nodes(node):
                new_stack = stack + [node] if isinstance(
                    node, (ast.ClassDef, ast.FunctionDef,
                           ast.AsyncFunctionDef)) else stack
                visit(child, new_stack)

        visit(sf.tree, [])

    def _class_root(self, rel, cls):
        """Canonical class for `self.<attr>` lock attribution: the
        ROOT of `cls`'s same-file single-inheritance chain. A mixin's
        `with self._cv:` and the subclass __init__ that registered
        the field are ONE lock per instance (serving.py's
        `_SchedulerLifecycle.drain` vs the engines' `_cv`) — without
        the canonical owner they would never meet. Unrelated classes
        (no same-file base) keep their own name, so two engines'
        `self._lock` stay distinct; multiple same-file bases stop the
        walk (no unambiguous root)."""
        bases = self._class_bases.get(rel, {})
        seen = {cls}
        while True:
            same_file = [b for b in bases.get(cls, ()) if b in bases]
            if len(same_file) != 1 or same_file[0] in seen:
                return cls
            cls = same_file[0]
            seen.add(cls)

    def _target_lock_id(self, rel, target, cls, fn):
        if isinstance(target, ast.Name):
            if fn is None and cls is None:
                return f"{rel}:{target.id}"
            return f"{rel}:{cls + '.' if cls else ''}" \
                   f"{fn + '.' if fn else ''}{target.id}"
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and cls:
            return f"{rel}:{self._class_root(rel, cls)}.{target.attr}"
        return None

    def _collect_functions(self, sf):
        def visit(node, class_name, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name,
                          f"{prefix}{child.name}.")
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    key = f"{sf.rel}:{qual}"
                    info = FunctionInfo(key, sf, qual, class_name,
                                        child)
                    self.functions[key] = info
                    self._method_defs.setdefault(
                        child.name, []).append(key)
                    # nested defs belong to the enclosing function's
                    # file scope; record them too (thread closures)
                    visit(child, class_name, f"{qual}.")

        visit(sf.tree, None, "")

    # -- lock identity -----------------------------------------------

    def lock_id(self, sf, expr, class_name, func_qualname):
        """The attributed identity of a lock-valued expression, or
        None when `expr` does not resolve to a known lock. `self._x`
        binds to the enclosing class, module globals to the module,
        locals to the enclosing function — two engines' `self._lock`
        stay distinct nodes in the graph."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and class_name:
            # inheritance: registration canonicalizes self-fields to
            # the class's same-file ROOT ancestor (_class_root), so a
            # mixin's `with self._cv:` and the subclass __init__ that
            # assigned it resolve to the same identity
            root = self._class_root(sf.rel, class_name)
            lid = f"{sf.rel}:{root}.{expr.attr}"
            if lid in self.locks:
                return lid
            lid = f"{sf.rel}:{class_name}.{expr.attr}"
            if lid in self.locks:
                return lid
            suffix = f".{expr.attr}"
            cands = [k for k in self._attr_locks
                     if k.startswith(f"{sf.rel}:") and
                     k.endswith(suffix)]
            return cands[0] if len(cands) == 1 else None
        if isinstance(expr, ast.Name):
            if expr.id in self._module_locks.get(sf.rel, ()):
                return f"{sf.rel}:{expr.id}"
            if func_qualname:
                lid = f"{sf.rel}:{func_qualname}.{expr.id}"
                if lid in self.locks:
                    return lid
                # nested function referring to an ENCLOSING function's
                # local lock (closure): the candidate's owner qualname
                # must be a prefix of ours — a parameter that merely
                # shares a class field's name must NOT resolve
                suffix = f".{expr.id}"
                pre = f"{sf.rel}:"
                cands = []
                for k in self._local_locks:
                    if not (k.startswith(pre) and k.endswith(suffix)):
                        continue
                    owner = k[len(pre):-len(suffix)]
                    if func_qualname == owner or \
                            func_qualname.startswith(owner + "."):
                        cands.append(k)
                if len(cands) == 1:
                    return cands[0]
            return None
        dotted = _dotted(expr)
        if dotted and "." in dotted:
            head, _, tail = dotted.partition(".")
            target = self.resolve_module(sf.rel, head)
            if target:
                lid = f"{target}:{tail}"
                if lid in self.locks:
                    return lid
        return None

    # -- call resolution ---------------------------------------------

    def resolve_module(self, rel, alias):
        """rel-path of the analyzed module an import alias points to,
        when the basename resolves uniquely; else None."""
        base = self._aliases.get(rel, {}).get(alias)
        if not base:
            return None
        cands = self._basenames.get(base, [])
        return cands[0] if len(cands) == 1 else None

    def resolve_call(self, sf, call, class_name):
        """The FunctionInfo key a call lands on, or None.

        Resolution ladder (documented in docs/STATIC_ANALYSIS.md):
        `self.m()` -> same-class method; bare `f()` -> same-module
        function; `alias.f()` -> the aliased in-tree module's
        function; `obj.m()` -> the ONE analyzed method of that name
        when the name is defined exactly once project-wide (the
        receiver's class is statically unknown; a unique definition
        makes the target unambiguous anyway)."""
        func = call.func
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and \
                    func.value.id == "self" and class_name:
                key = f"{sf.rel}:{class_name}.{func.attr}"
                if key in self.functions:
                    return key
            dotted = _dotted(func.value)
            if dotted and "." not in dotted:
                target = self.resolve_module(sf.rel, dotted)
                if target:
                    key = f"{target}:{func.attr}"
                    if key in self.functions:
                        return key
            # unique-definition fallback — never for dunders, and
            # never for names shadowing builtin container/stdlib
            # methods: `somedict.get(k)` must not resolve to the one
            # project class that happens to define `get`, fabricating
            # call-graph edges
            if not func.attr.startswith("__") and \
                    func.attr not in _BUILTIN_METHOD_NAMES:
                defs = self._method_defs.get(func.attr, [])
                if len(defs) == 1:
                    return defs[0]
            return None
        if isinstance(func, ast.Name):
            key = f"{sf.rel}:{func.id}"
            if key in self.functions:
                return key
        return None

    # -- per-function lock/call summaries ----------------------------

    def lock_flow(self, sf, node, class_name, qualname):
        """(acquired, released) lock-id sets from EXPLICIT
        `.acquire()` / `.release()` calls in node's subtree (nested
        defs excluded). The sequential complement of `with` tracking:
        a lock .acquire()d in one statement stays held for the REST
        of the suite until a statement .release()s it — the bounded-
        acquire diagnosis idiom (`if lock.acquire(timeout=...):
        try: ... finally: lock.release()`) must not exempt its body
        from every held-lock rule."""
        acq, rel = set(), set()
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not node:
                continue  # nested defs run later, not in this flow
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("acquire", "release"):
                lid = self.lock_id(sf, n.func.value, class_name,
                                   qualname)
                if lid is None:
                    recv = _last_attr(n.func.value) or ""
                    if re.search(r"(lock|_cv|cond|gate|sem)", recv,
                                 re.I):
                        lid = f"{sf.rel}:<{recv}>"
                if lid:
                    (acq if n.func.attr == "acquire" else rel).add(lid)
            stack.extend(ast.iter_child_nodes(n))
        return acq, rel

    def build_summaries(self, effect_extractor=None):
        """Fill every FunctionInfo's acquisitions/calls (+ direct
        effects via `effect_extractor(sf, node, held)` returning
        [(rule, label, line)]). Memoized: a summary built WITH an
        extractor is a superset of one built without (the extractor
        only adds `effects`), so repeat calls — the passes share one
        ProjectContext — rebuild only when an extractor arrives after
        an extractor-less build."""
        if self._summaries_extractor is not None and (
                effect_extractor is None or
                effect_extractor is self._summaries_extractor):
            return self.functions
        if self._summaries_extractor is False and \
                effect_extractor is None:
            return self.functions
        for info in self.functions.values():
            info.acquisitions = []
            info.calls = []
            info.effects = []
            self._summarize(info, effect_extractor)
        self._summaries_extractor = effect_extractor \
            if effect_extractor is not None else False
        return self.functions

    def _summarize(self, info, effect_extractor):
        sf = info.file
        # cheap gate: sequential explicit-acquire tracking rescans
        # child subtrees, so skip it for the (vast majority of) files
        # with no explicit .acquire( anywhere
        track_explicit = ".acquire(" in sf.text

        def walk(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not info.node:
                return  # nested defs summarized as their own functions
            new_held = held
            if isinstance(node, ast.With):
                # items acquire LEFT to RIGHT: `with a, b:` holds a
                # at b's acquisition — the held tuple grows per item
                for item in node.items:
                    lid = self.lock_id(sf, item.context_expr,
                                       info.class_name, info.qualname)
                    if lid:
                        info.acquisitions.append(
                            (lid, item.context_expr.lineno, True,
                             False, new_held))
                        new_held = new_held + (lid,)
            elif isinstance(node, ast.Call):
                last = _last_attr(node.func)
                if last == "acquire" and isinstance(node.func,
                                                   ast.Attribute):
                    lid = self.lock_id(sf, node.func.value,
                                       info.class_name, info.qualname)
                    has_timeout = _acquire_is_bounded(node)
                    if lid is None:
                        # unresolved receiver with a lock-shaped name
                        # (a parameter-passed lock): still subject to
                        # the unbounded-acquire rule
                        recv = _last_attr(node.func.value) or ""
                        if re.search(r"(lock|_cv|cond|gate|sem)",
                                     recv, re.I):
                            lid = f"{sf.rel}:<{recv}>"
                    if lid:
                        info.acquisitions.append(
                            (lid, node.lineno, False, has_timeout,
                             held))
                key = self.resolve_call(sf, node, info.class_name)
                label = _dotted(node.func) or (last or "?")
                info.calls.append((key, held, node.lineno, label))
                if effect_extractor is not None:
                    for rule, lab, line in effect_extractor(
                            sf, node, held) or ():
                        info.effects.append((rule, lab, line, held))
            if effect_extractor is not None and not isinstance(
                    node, ast.Call):
                for rule, lab, line in effect_extractor(
                        sf, node, held) or ():
                    info.effects.append((rule, lab, line, held))
            # children run in source order; an explicit .acquire() in
            # one child holds the lock for the SIBLINGS that follow
            # (until a sibling .release()s it) — `if lock.acquire():`
            # walks the If body with the lock held via the test's
            # acquire, and the try/finally release drops it after
            run = new_held
            for child in ast.iter_child_nodes(node):
                walk(child, run)
                if track_explicit:
                    acq, rel = self.lock_flow(
                        sf, child, info.class_name, info.qualname)
                    if acq or rel:
                        run = tuple(l for l in run if l not in rel) \
                            + tuple(l for l in sorted(acq)
                                    if l not in run and l not in rel)

        walk(info.node, ())

    def held_at_acquisitions(self):
        """[(holder_lock_id, acquired_lock_id, file, line, via)] edges
        from DIRECT lexical nesting — read off the summaries' held
        tuples (one walk, `_summarize`, owns the held-lock
        propagation rules)."""
        self.build_summaries()
        edges = []
        for info in self.functions.values():
            for lid, line, _with, _t, held in info.acquisitions:
                if "<" in lid:
                    continue  # pseudo-id (unresolved receiver)
                for h in held:
                    edges.append((h, lid, info.file.rel, line, None))
        return edges


def transitive_closure(seeds, calls_of, cap=64):
    """Fixpoint expansion of per-function fact sets through the call
    graph: `seeds[key]` grows by every resolvable callee's set until
    stable. Recursion converges (set union is monotonic); `cap` bounds
    a runaway set so pathological generated code cannot wedge the
    linter. Shared by the lock-order and blocking-under-lock passes —
    one copy of the termination/cap behavior."""
    changed = True
    while changed:
        changed = False
        for key, acc in seeds.items():
            if len(acc) >= cap:
                continue
            for callee in calls_of(key):
                if callee is not None and callee in seeds:
                    new = seeds[callee] - acc
                    if new:
                        acc |= new
                        changed = True
    return seeds


# -- suppression engine -------------------------------------------------

def apply_suppressions(ctx, findings):
    """Mark findings suppressed where a scoped/unscoped `# lint-ok:`
    marker with a NON-EMPTY reason sits on the finding's line; emit
    `suppression-needs-reason` findings for reasonless markers (both
    lint-ok and the hot-sync pass's hot-sync-ok). Returns the full
    finding list (suppression findings appended)."""
    by_rel = {sf.rel: sf for sf in ctx.files}
    for f in findings:
        sf = by_rel.get(f.file)
        if sf is None or f.suppressed:
            continue
        mark = sf.lint_ok.get(f.line)
        if mark is None:
            continue
        scope, reason = mark
        if scope is not None and scope != f.pass_name:
            continue
        if scope is None and f.pass_name == "hot-sync":
            # the hot-sync fence accepts only its own markers
            # (hot-sync-ok, or the explicitly scoped lint-ok[hot-sync]
            # the legacy check_source honors too) — an unscoped
            # lint-ok must not blank a sync check the shim CLI would
            # still flag
            continue
        if reason:
            f.suppressed = True
            f.reason = reason
    out = list(findings)
    for sf in ctx.files:
        # marker-free files (the vast majority) skip the AST walk and
        # the line scan entirely
        has_hot_marker = "hot-sync-ok" in sf.text
        if not sf.lint_ok and not has_hot_marker:
            continue
        strings = sf.string_lines()
        for i, (scope, reason) in sorted(sf.lint_ok.items()):
            if not reason and i not in strings:
                out.append(Finding(
                    "suppression", "suppression-needs-reason", sf.rel,
                    i, "lint-ok marker without a reason — a "
                    "suppression must say WHY (# lint-ok: <why>)"))
        if not has_hot_marker:
            continue
        for i, line in enumerate(sf.lines, 1):
            if "hot-sync-ok" in line and i not in strings and \
                    "#" in line:
                m = HOT_SYNC_OK_RE.search(line)
                if m is not None and not m.group("reason").strip():
                    out.append(Finding(
                        "suppression", "suppression-needs-reason",
                        sf.rel, i, "hot-sync-ok marker without a "
                        "reason — a suppression must say WHY "
                        "(# hot-sync-ok: <why>)"))
    return out


# -- baseline ratchet ---------------------------------------------------

BASELINE_SCHEMA = "paddle_tpu.lint_baseline.v1"


def suppressed_counts(findings):
    counts = {}
    for f in findings:
        if f.suppressed:
            counts[f.pass_name] = counts.get(f.pass_name, 0) + 1
    return counts


def load_baseline(path):
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or \
            not isinstance(payload.get("passes"), dict):
        return None
    return payload


def check_baseline(baseline, counts, selected):
    """Ratchet verdicts: [error strings] for selected passes whose
    CURRENT suppressed count exceeds the baseline. New suppressions
    require a hand edit of LINT_BASELINE.json (visible in review);
    `--update` only ever writes counts that got SMALLER."""
    errors = []
    passes = baseline.get("passes", {})
    for name in selected:
        cur = counts.get(name, 0)
        base = passes.get(name, {}).get("suppressed")
        if base is None:
            errors.append(
                f"LINT_BASELINE.json has no entry for pass {name!r} — "
                f"add one (suppressed: {cur})")
        elif cur > base:
            errors.append(
                f"pass {name!r}: {cur} suppressed finding(s) exceeds "
                f"the baseline {base} — new suppressions must raise "
                "the baseline by hand, in the diff")
    return errors


def update_baseline(path, baseline, counts, selected):
    """Ratchet DOWN only: rewrite entries whose current count is lower
    than the recorded one. Returns (wrote, refused) — `refused` lists
    passes whose counts grew OR whose entry is missing (a new pass's
    entry is added BY HAND, in the diff, like any other loosening —
    --update never creates one)."""
    passes = baseline.get("passes", {})
    wrote, refused = False, []
    for name in selected:
        cur = counts.get(name, 0)
        entry = passes.get(name)
        base = entry.get("suppressed") if entry else None
        if base is None:
            refused.append(name)
        elif cur < base:
            entry["suppressed"] = cur
            wrote = True
        elif cur > base:
            refused.append(name)
    if wrote:
        baseline["schema"] = BASELINE_SCHEMA
        baseline["recorded_utc"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with open(path, "w") as f:
            json.dump(baseline, f, indent=1, sort_keys=True)
            f.write("\n")
    return wrote, refused


# -- fileset ------------------------------------------------------------

EXCLUDE_DIRS = {"__pycache__", ".git", "fixtures"}


def default_fileset(root):
    """The analyzed set: paddle_tpu/**, tools/** (the linter's own
    fixtures excluded — they are known-bad on purpose), bench.py."""
    rels = []
    for top in ("paddle_tpu", "tools"):
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d not in EXCLUDE_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rels.append(os.path.relpath(
                        os.path.join(dirpath, fn), root))
    if os.path.isfile(os.path.join(root, "bench.py")):
        rels.append("bench.py")
    return rels


def walk_fileset(root):
    """Fileset for an arbitrary root (fixture corpora): every .py under
    it."""
    rels = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rels.append(os.path.relpath(os.path.join(dirpath, fn),
                                            root))
    return rels
