"""lock-order pass: the static deadlock detector.

Extracts every `with <lock>:` / `<lock>.acquire()` site across the
analyzed fileset, attributes each lock to its owning scope
(`self._lock` in two engines stays two distinct graph nodes; module
globals bind to their module; function locals to their function), and
builds the cross-module lock-ACQUISITION graph: an edge A -> B means
"some code path acquires B while holding A". Two edge sources:

- **lexical nesting** — a `with B:` (or `B.acquire()`) inside the body
  of a `with A:`;
- **call expansion** — a call made while holding A whose callee
  (resolved per core.ProjectContext.resolve_call: self-methods,
  module functions, imported in-tree modules, unique-definition
  methods) transitively acquires B. The transitive acquire sets are a
  fixpoint over the per-function summaries, so recursion and
  cross-module chains (engine -> monitor -> flight_recorder) converge.

Verdicts:

- `lock-cycle` — a strongly connected component with >= 2 locks: two
  threads taking the locks in opposite orders can deadlock. The
  finding names every edge of the cycle with its file:line.
- `lock-self-cycle` — a non-reentrant `threading.Lock` re-acquired
  while already held (lexically, or via a resolved call chain): a
  single thread wedges itself. Reentrant kinds (RLock, Condition —
  Condition wraps an RLock) are exempt by construction.

False positives (a cycle the runtime provably never interleaves) get a
`# lint-ok[lock-order]: <why>` on the acquisition line — never a
weakened rule. See docs/STATIC_ANALYSIS.md.
"""
from .core import Finding, REENTRANT_KINDS, transitive_closure

PASS_NAME = "lock-order"

# transitive-acquire set size cap: a runaway summary (pathological
# generated code) must not wedge the linter
_MAX_ACQ = 64


class LockOrderPass:
    name = PASS_NAME

    def run(self, ctx):
        ctx.build_summaries()
        edges = {}  # (a, b) -> (file, line, via_label)

        # 1) direct lexical nesting
        for a, b, rel, line, _ in ctx.held_at_acquisitions():
            if a == b and ctx.locks.get(a) in REENTRANT_KINDS:
                continue
            edges.setdefault((a, b), (rel, line, None))

        # 2) call expansion: transitive acquires per function (fixpoint)
        # pseudo-ids ("<recv>": parameter-passed locks the resolver
        # could not attribute) stay out of the graph — they unify by
        # receiver NAME, which would fabricate cycles
        acquires = transitive_closure(
            {key: {a for a, *_ in info.acquisitions if "<" not in a}
             for key, info in ctx.functions.items()},
            lambda key: (c for c, _, _, _ in
                         ctx.functions[key].calls),
            cap=_MAX_ACQ)
        for key, info in ctx.functions.items():
            for callee, held, line, label in info.calls:
                if not callee or not held or callee not in acquires:
                    continue
                for b in acquires[callee]:
                    for a in held:
                        if a == b:
                            continue  # self via call: handled below
                        edges.setdefault(
                            (a, b), (info.file.rel, line,
                                     f"via {label}() -> {callee}"))
                # self-cycle via call chain on a plain Lock
                for a in held:
                    if a in acquires[callee] and \
                            ctx.locks.get(a) not in REENTRANT_KINDS:
                        edges.setdefault(
                            (a, a), (info.file.rel, line,
                                     f"via {label}() -> {callee}"))

        return self._verdicts(ctx, edges)

    def _verdicts(self, ctx, edges):
        findings = []
        graph = {}
        for (a, b), site in edges.items():
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        # self-cycles first (definite single-thread wedge)
        for (a, b), (rel, line, via) in sorted(edges.items()):
            if a == b:
                kind = ctx.locks.get(a, "Lock")
                findings.append(Finding(
                    PASS_NAME, "lock-self-cycle", rel, line,
                    f"non-reentrant {kind} {a} re-acquired while "
                    f"already held"
                    + (f" ({via})" if via else " (lexical nesting)")))
        # multi-lock cycles: Tarjan SCC
        for scc in _sccs(graph):
            if len(scc) < 2:
                continue
            cyc_edges = sorted(
                (a, b) for (a, b) in edges
                if a in scc and b in scc and a != b)
            detail = "; ".join(
                f"{a} -> {b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
                + (f" ({edges[(a, b)][2]})" if edges[(a, b)][2] else "")
                for a, b in cyc_edges)
            rel, line, _ = edges[cyc_edges[0]]
            findings.append(Finding(
                PASS_NAME, "lock-cycle", rel, line,
                f"lock-acquisition cycle across {len(scc)} locks "
                f"({', '.join(sorted(scc))}): {detail}"))
        return findings


def _sccs(graph):
    """Tarjan's strongly connected components (iterative)."""
    index_counter = [0]
    stack, lowlink, index, on_stack = [], {}, {}, set()
    result = []

    def strongconnect(v0):
        work = [(v0, iter(sorted(graph.get(v0, ()))))]
        while work:
            v, it = work[-1]
            if v not in index:
                index[v] = lowlink[v] = index_counter[0]
                index_counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            advanced = False
            for w in it:
                if w not in index:
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    lowlink[v] = min(lowlink[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[v])
            if lowlink[v] == index[v]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == v:
                        break
                result.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return result
