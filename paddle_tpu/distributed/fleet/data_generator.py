"""Data generators for the MultiSlot text feed. Parity:
python/paddle/distributed/fleet/data_generator/data_generator.py.

Pure-Python text protocol: user overrides ``generate_sample`` (and
optionally ``generate_batch``); ``run_from_stdin`` / ``run_from_files``
stream lines through it and emit the MultiSlot wire format
``<ids_num> id1 id2 ... per slot`` consumable by dataset readers
(io/ps_dataset.py).
"""
import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 1

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """Override: return a no-arg iterator yielding
        [(slot_name, [feasign, ...]), ...] per sample."""
        raise NotImplementedError(
            "generate_sample() must be overridden by the user")

    def generate_batch(self, samples):
        def local_iter():
            for sample in samples:
                yield sample
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def _run_lines(self, lines, out):
        batch = []
        for line in lines:
            for parsed in self.generate_sample(line)():
                if parsed is None:
                    continue
                batch.append(parsed)
                if len(batch) == self.batch_size_:
                    for sample in self.generate_batch(batch)():
                        out.write(self._gen_str(sample))
                    batch = []
        if batch:
            for sample in self.generate_batch(batch)():
                out.write(self._gen_str(sample))

    def run_from_stdin(self):
        self._run_lines(sys.stdin, sys.stdout)

    def run_from_files(self, filelist, output=None):
        out = output or sys.stdout
        for fname in filelist:
            with open(fname) as f:
                self._run_lines(f, out)


def _format_slots(line, stringify):
    if isinstance(line, zip):
        line = list(line)
    if not isinstance(line, (list, tuple)):
        raise ValueError(
            "the output of generate_sample() must be a list or tuple, "
            "e.g. [('words', [1926, 8, 17]), ('label', [1])]")
    parts = []
    for name, elements in line:
        vals = [str(e) for e in elements] if stringify else list(elements)
        parts.append(" ".join([str(len(vals))] + [str(v) for v in vals]))
    return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Slots carry int/float feasigns."""

    def _gen_str(self, line):
        return _format_slots(line, stringify=True)


class MultiSlotStringDataGenerator(DataGenerator):
    """Slots carry pre-stringified feasigns (no type coercion)."""

    def _gen_str(self, line):
        return _format_slots(line, stringify=False)
