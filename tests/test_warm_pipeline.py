"""The compile pipeline (ISSUE 7): background AOT compilation with
single-flight dedup, compile-cache pack/seed, executable-sharing
warmup, and the warm-set wall-clock gate.

Proof points:
- two threads requesting the same (tag, signature) produce ONE compile
  and ONE ledger record (single-flight dedup), and a dispatch racing a
  warm() joins the in-flight compile instead of recompiling;
- a warm set's executables compile OVERLAPPED: the `kind:"warm"`
  record's wall_s lands well under the sum of per-executable seconds
  (calibrated best-of-3 on the 2-CPU container);
- warming uses exactly the steady-state abstract signatures: steady
  traffic after a warm adds ZERO (tag, signature) pairs to the
  compilation observatory's ledger — TrainStep flavors and serving
  buckets alike;
- `compile_cache.pack` -> fresh subprocess -> `seed_from` roundtrip:
  the seeded process compiles the same workload as all-cache-hit
  ledger records (near-zero compile_s, cache_entries_added == 0) and
  exports a valid `kind:"seed"` record;
- concurrent compiles keep exact hit/miss attribution (the racy
  entry-set diff around overlapping compiles is fixed via jax's
  per-thread cache events + a claimed-entries ledger);
- tools/check_metrics_schema.py validates (and rejects malformed)
  warm/seed records; tools/check_compile_budget.py gates the warm-set
  wall-clock against BASELINE_HLO.json and only ever ratchets tighter;
- bench.py seeds from BENCH_CACHE_SEED (pure file copies in the
  parent) and rolls unused attempt budget over.
"""
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.framework import compile_cache
from paddle_tpu.jit import TrainStep, warm
from paddle_tpu.profiler import (statistic, monitor, flight_recorder,
                                 compile_observatory)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    path = os.path.join(REPO, "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    statistic.reset_statistics()
    monitor.reset_metrics()
    flight_recorder.reset()
    compile_observatory.reset()
    yield


def _mse(a, b):
    return ((a - b) ** 2).mean()


def _make_step(width=16, seed=0, n=8):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, width), nn.ReLU(), nn.Linear(width, 4))
    o = opt.SGD(learning_rate=0.05, parameters=m.parameters())
    step = TrainStep(m, _mse, o)
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.randn(n, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randn(n, 4).astype(np.float32))
    return step, x, y


def _recs(path, kind="compile", tag=None):
    recs = [json.loads(l) for l in open(path) if l.strip()]
    out = [r for r in recs if r.get("kind") == kind]
    return [r for r in out if r["tag"] == tag] if tag else out


# --------------------------------------------------- single-flight dedup
def test_single_flight_dedup_one_ledger_record(tmp_path, monkeypatch):
    """N threads warming one (tag, signature) concurrently -> one
    compile, one ledger record, one executable; the extra requests JOIN
    the flight (warm.joined counts them) and all resolve to the same
    entry."""
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    step, x, y = _make_step()
    handles = []
    lock = threading.Lock()

    def w():
        h = step.warm(x, y)
        with lock:
            handles.append(h)

    threads = [threading.Thread(target=w) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    entries = {id(h.result(timeout=120)) for h in handles}
    assert len(entries) == 1          # every handle resolved to ONE entry
    assert len(_recs(mfile, tag="train.step")) == 1
    assert len(step._exec) == 1
    # at least one request joined an existing flight (the first
    # submitted; with 4 racers some must have deduped)
    assert monitor.counter("warm.joined").value >= 1
    assert monitor.counter("warm.submitted").value == 1
    # the warmed executable is the one dispatch uses: training works and
    # records no further compile
    float(step(x, y).item())
    assert len(_recs(mfile, tag="train.step")) == 1


def test_dispatch_joins_inflight_warm(tmp_path, monkeypatch):
    """__call__ issued while warm() is still compiling must block only
    on that one executable — and produce no duplicate ledger record."""
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    step, x, y = _make_step(width=32, seed=1)
    h = step.warm(x, y)               # background compile starts
    loss = float(step(x, y).item())   # dispatch joins the flight
    assert np.isfinite(loss)
    assert h.done()
    assert len(_recs(mfile, tag="train.step")) == 1
    assert step.retraces == 1


def test_dispatch_miss_never_queues_behind_unrelated_warms():
    """A dispatch-path miss compiles INLINE on the calling thread when
    it wins the single-flight race — it must not sit in the executor
    queue behind unrelated background warms. With every worker pinned
    by slow thunks, a fresh dispatch still completes in a fraction of
    their runtime."""
    n = warm.workers() + 2

    def sleeper():
        time.sleep(6)
        return ("x", {"lower_s": 0.0, "compile_s": 6.0,
                      "cache_hit": False})

    blocked = [warm.submit((f"slow{i}", i), f"slow{i}", sleeper)[0]
               for i in range(n)]
    try:
        step, x, y = _make_step(width=24, seed=7)
        t0 = time.perf_counter()
        loss = float(step(x, y).item())   # miss -> inline compile
        dt = time.perf_counter() - t0
        assert np.isfinite(loss)
        # generous bound: the tiny-step compile is well under a second;
        # queueing behind even one 6s sleeper would blow past this
        assert dt < 5.0, f"dispatch waited {dt:.1f}s behind warm queue"
    finally:
        warm.join(blocked, record=False)


def test_warm_handle_error_propagates_and_retries():
    """A failing compile thunk rejects every joiner with the real error
    and leaves the flight closed, so a retry compiles fresh."""
    calls = []

    def bad():
        calls.append(1)
        raise RuntimeError("boom in compile")

    h, submitted = warm.submit(("t", "sig"), "t", bad)
    assert submitted
    with pytest.raises(RuntimeError, match="boom in compile"):
        h.result(timeout=60)
    # the failed flight closed: a new submit runs the thunk again
    h2, submitted2 = warm.submit(("t", "sig"), "t", lambda: ("ok", {}))
    assert submitted2
    assert h2.result(timeout=60)[0] == "ok"
    assert calls == [1]


# ------------------------------------------- executable-sharing warmup
@pytest.mark.heavy
def test_warmup_adds_zero_executables_beyond_steady_state(tmp_path,
                                                          monkeypatch):
    """Warm the full executable set (per-step, run_steps, accumulate,
    serving buckets), then run steady-state traffic: the observatory
    ledger must gain ZERO (tag, signature) pairs — warmup shapes ARE
    the steady-state shapes."""
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    step, x, y = _make_step(seed=2)
    xs = paddle.to_tensor(np.stack([x.numpy()] * 2))
    ys = paddle.to_tensor(np.stack([y.numpy()] * 2))
    from paddle_tpu.inference import InferenceEngine
    paddle.seed(2)
    eng = InferenceEngine(nn.Linear(8, 4), batch_sizes=(1, 2),
                          name="wp")
    try:
        handles = [step.warm(x, y),
                   step.warm_run_steps(2, x, y),
                   step.warm_accumulate(2, xs, ys)]
        handles += eng.warm_async(np.zeros((1, 8), np.float32))
        summary = warm.join(handles)
        assert summary["n_executables"] == 5
        assert summary["compiled_now"] == 5
        warmed = compile_observatory.ledger_signatures()
        assert len(warmed) == 5

        # steady state: every path reuses a warmed executable
        float(step(x, y).item())
        step.run_steps(2, x, y)
        float(step.accumulate(2, xs, ys).item())
        eng(np.zeros((1, 8), np.float32))
        assert compile_observatory.ledger_signatures() == warmed
    finally:
        eng.shutdown()
    # the already-warm set joins as instantly-done handles with zero
    # marginal cost
    again = warm.join([step.warm(x, y),
                       step.warm_run_steps(2, x, y)], record=False)
    assert again["compiled_now"] == 0
    assert again["sum_s"] == 0.0


@pytest.mark.heavy
def test_warm_set_compiles_overlapped(tmp_path, monkeypatch):
    """The warm set's wall-clock must land meaningfully under the sum
    of its per-executable compile seconds — the overlap the background
    executor exists for. Calibrated best-of-3 on the 2-CPU container
    (host 'weather' can serialize any single round): one clean round
    passes; the failure message carries every round's numbers."""
    if warm.workers() < 2:
        pytest.skip("compile overlap needs >= 2 warm workers; this "
                    f"container gives {warm.workers()} (1 CPU) — wall "
                    "== sum is physics here, not a regression")
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    rounds = []
    for rnd in range(3):
        compile_observatory.reset()
        paddle.seed(10 + rnd)  # fresh params -> fresh executables
        m = nn.Sequential(nn.Linear(64, 128), nn.Tanh(),
                          nn.Linear(128, 64), nn.Tanh(),
                          nn.Linear(64, 8))
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, _mse, o)
        rng = np.random.RandomState(rnd)
        x = paddle.to_tensor(rng.randn(16, 64).astype(np.float32))
        y = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        xs = paddle.to_tensor(np.stack([x.numpy()] * 2))
        ys = paddle.to_tensor(np.stack([y.numpy()] * 2))
        s = warm.join([step.warm(x, y),
                       step.warm_run_steps(2, x, y),
                       step.warm_accumulate(2, xs, ys)])
        rounds.append(s)
        # meaningful compiles (not measuring thread overhead) that
        # finished wall-clock under 90% of their serial cost
        if s["sum_s"] > 0.5 and s["wall_s"] < 0.9 * s["sum_s"]:
            break
    else:
        pytest.fail(
            "no round overlapped: " + "; ".join(
                f"wall {r['wall_s']:.2f}s vs sum {r['sum_s']:.2f}s"
                for r in rounds))
    # the evidence rode into the metrics JSONL as kind:"warm" records
    # and the whole file validates
    wrecs = _recs(mfile, kind="warm")
    assert len(wrecs) == len(rounds)
    assert wrecs[-1]["n_executables"] == 3
    cms = _load_tool("check_metrics_schema")
    assert cms.validate_file(str(mfile)) == []


# --------------------------------------------------- pack/seed roundtrip
_SEED_CHILD = """
import json, os, sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.jit import TrainStep
from paddle_tpu.framework import compile_cache

mode = sys.argv[1]
if mode == "seed":
    info = compile_cache.seed_from(sys.argv[2])
    print("seed-info: " + json.dumps(info), file=sys.stderr)

paddle.seed(0)
m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
step = TrainStep(
    m, lambda out, y: nn.functional.cross_entropy(out, y), o)
x = paddle.to_tensor(
    np.random.RandomState(0).randn(4, 16).astype(np.float32))
y = paddle.to_tensor(np.arange(4, dtype=np.int64) % 8)
float(step(x, y).item())
step.run_steps(2, x, y)

if mode == "pack":
    out = compile_cache.pack(sys.argv[2])
    print(json.dumps({"packed": out["entries"]}))
else:
    print(json.dumps({"entries": len(compile_cache.cache_entry_names())}))
"""


@pytest.mark.heavy
def test_pack_seed_roundtrip_fresh_subprocess(tmp_path):
    """Process 1 compiles cold under cache A and packs it; process 2 —
    fresh, with a DIFFERENT cache dir — seeds from the pack and must
    compile the same workload as all-cache-hit records adding zero
    entries. This is the donated-artifact warm start (and proves cache
    keys don't hash the cache path)."""

    def run(mode, cache, extra, idx):
        mfile = tmp_path / f"metrics{idx}.jsonl"
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                    "PADDLE_TPU_COMPILE_CACHE": str(cache),
                    "PADDLE_TPU_METRICS_FILE": str(mfile),
                    "PYTHONUNBUFFERED": "1"})
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "-c", _SEED_CHILD, mode, str(extra)],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=240)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("{")][-1]
        return json.loads(line), mfile, proc.stderr

    pack_dir = tmp_path / "artifact"
    out1, m1, _ = run("pack", tmp_path / "cacheA", pack_dir, 1)
    assert out1["packed"] >= 2          # step + run_steps at least
    assert (pack_dir / "MANIFEST.json").exists()
    manifest = json.loads((pack_dir / "MANIFEST.json").read_text())
    assert manifest["schema"] == compile_cache.PACK_SCHEMA
    assert len(manifest["entries"]) == out1["packed"]
    recs1 = _recs(m1)
    assert recs1 and all(r["cache_hit"] is False for r in recs1)

    out2, m2, err2 = run("seed", tmp_path / "cacheB", pack_dir, 2)
    recs2 = _recs(m2)
    assert {r["tag"] for r in recs2} == {"train.step",
                                         "train.run_steps"}
    cms = _load_tool("check_metrics_schema")
    for r in recs2:
        # all-cache-hit, zero new entries, near-zero compile seconds
        assert r["cache_hit"] is True, r
        assert r["cache_entries_added"] == 0, r
        assert r["compile_s"] <= cms.CACHE_HIT_COMPILE_S_MAX
    # the seed itself exported a valid kind:"seed" record
    seeds = _recs(m2, kind="seed")
    assert len(seeds) == 1
    assert seeds[0]["entries_seeded"] == out1["packed"]
    assert seeds[0]["entries_skipped"] == 0
    assert cms.validate_file(str(m2)) == []
    # and the seeded cache gained nothing beyond the artifact
    assert out2["entries"] == out1["packed"]


_ATTR_CHILD = """
import json, threading
import jax, jax.numpy as jnp
from paddle_tpu.framework import compile_cache
from paddle_tpu.jit.api import aot_compile
from paddle_tpu.profiler import compile_observatory as cobs

x = jnp.ones((96, 96))
def go(tag, f):
    aot_compile(jax.jit(f), (x,), tag=tag)

# phase 1: two DIFFERENT programs compile concurrently (miss + miss)
t1 = threading.Thread(target=go, args=("m1", lambda a: a @ a + 1.0))
t2 = threading.Thread(target=go, args=("m2", lambda a: (a * 2) @ a.T))
t1.start(); t2.start(); t1.join(); t2.join()
# phase 2: a HIT for m1's program overlapping a fresh MISS — the racy
# window the entry-set diff used to misattribute
t3 = threading.Thread(target=go, args=("hit", lambda a: a @ a + 1.0))
t4 = threading.Thread(target=go, args=("m3", lambda a: jnp.tanh(a) @ a))
t3.start(); t4.start(); t3.join(); t4.join()
recs = {r["tag"]: {"hit": r["cache_hit"],
                   "added": r["cache_entries_added"]}
        for r in cobs.ledger()}
print(json.dumps({"recs": recs,
                  "disk": len(compile_cache.cache_entry_names())}))
"""


@pytest.mark.heavy
def test_concurrent_cache_hit_attribution(tmp_path):
    """Overlapping compiles with the persistent cache ON: every record's
    hit/miss flag is exact (per-thread jax cache events), a hit claims
    zero entries even when a concurrent miss lands entries inside its
    window, and no entry is double-counted."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "PADDLE_TPU_COMPILE_CACHE": str(tmp_path / "cache"),
                "PYTHONUNBUFFERED": "1"})
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _ATTR_CHILD], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads([l for l in proc.stdout.splitlines()
                      if l.startswith("{")][-1])
    recs = out["recs"]
    # hit/miss flags are EXACT for every record; entry counts may shift
    # between overlapping misses (documented: one miss's window can
    # swallow the other's entries) but never double-count — per phase,
    # the misses' claims sum to at least one entry each on average and
    # a hit always claims zero
    assert recs["m1"]["hit"] is False and recs["m2"]["hit"] is False
    assert recs["m1"]["added"] + recs["m2"]["added"] >= 2
    assert recs["m3"]["hit"] is False and recs["m3"]["added"] >= 1
    # the racy case: the hit stays a hit and claims nothing, even with
    # the concurrent miss m3 landing entries inside its window
    assert recs["hit"]["hit"] is True
    assert recs["hit"]["added"] == 0


# ------------------------------------------------- schema + budget gate
def test_warm_and_seed_schema_validation():
    cms = _load_tool("check_metrics_schema")
    good_warm = {"ts": 1.0, "rank": 0, "kind": "warm",
                 "n_executables": 3, "compiled_now": 2, "cache_hits": 1,
                 "wall_s": 1.5, "sum_s": 4.0,
                 "tags": ["train.step", "train.run_steps"]}
    assert cms.validate_line(json.dumps(good_warm)) == []
    bad = dict(good_warm, compiled_now=5)
    assert any("compiled_now" in e
               for e in cms.validate_line(json.dumps(bad)))
    bad = dict(good_warm, cache_hits=3)
    assert any("cache_hits" in e
               for e in cms.validate_line(json.dumps(bad)))
    bad = dict(good_warm, wall_s=-0.1)
    assert any("wall_s" in e for e in cms.validate_line(json.dumps(bad)))
    bad = dict(good_warm)
    del bad["sum_s"]
    assert any("sum_s" in e for e in cms.validate_line(json.dumps(bad)))
    bad = dict(good_warm, tags=["ok", ""])
    assert any("tags" in e for e in cms.validate_line(json.dumps(bad)))

    good_seed = {"ts": 1.0, "rank": 0, "kind": "seed", "source": "/a",
                 "cache_dir": "/b", "entries_seeded": 4,
                 "entries_skipped": 0}
    assert cms.validate_line(json.dumps(good_seed)) == []
    bad = dict(good_seed, entries_seeded=-1)
    assert any("entries_seeded" in e
               for e in cms.validate_line(json.dumps(bad)))
    bad = dict(good_seed, source="")
    assert any("source" in e for e in cms.validate_line(json.dumps(bad)))
    bad = dict(good_seed)
    del bad["entries_skipped"]
    assert any("entries_skipped" in e
               for e in cms.validate_line(json.dumps(bad)))


def test_budget_gate_warm_set_comparand(tmp_path):
    """check_compile_budget's warm-set wall-clock comparand: green
    within budget, red (named) when the overlap breaks, ratcheted only
    tighter by --update."""
    cb = _load_tool("check_compile_budget")
    baseline = {"executables": {},
                "warm_set": {"wall_s": 2.0, "sum_s": 6.0,
                             "n_executables": 5}}
    ok = {"kind": "warm", "wall_s": 2.2, "sum_s": 6.0,
          "n_executables": 5}
    v, n, r = cb.compare_warm(baseline, ok, 2.5, 2.0, False)
    assert v == [] and r is None
    # regression: wall blew past base*factor+slack (overlap broke)
    slow = dict(ok, wall_s=2.0 * 2.5 + 2.0 + 1.0)
    v, n, r = cb.compare_warm(baseline, slow, 2.5, 2.0, False)
    assert len(v) == 1 and "warm_set" in v[0] and "overlap" in v[0]
    # faster run ratchets
    fast = dict(ok, wall_s=1.2)
    v, n, r = cb.compare_warm(baseline, fast, 2.5, 2.0, False)
    assert v == [] and r == {"wall_s": 1.2, "sum_s": 6.0,
                             "n_executables": 5}
    # a baseline with warm_set but a ledger without a warm record is a
    # violation only under --require-all
    v, n, r = cb.compare_warm(baseline, None, 2.5, 2.0, False)
    assert v == [] and n
    v, n, r = cb.compare_warm(baseline, None, 2.5, 2.0, True)
    assert len(v) == 1
    # the checked-in baseline carries the warm_set entry
    gc = _load_tool("_gate_common")
    payload = gc.load_baseline(os.path.join(REPO, "BASELINE_HLO.json"))
    assert payload["warm_set"]["wall_s"] > 0
    assert payload["warm_set"]["wall_s"] < payload["warm_set"]["sum_s"]


def test_gate_common_load_warm_record(tmp_path):
    gc = _load_tool("_gate_common")
    p = tmp_path / "l.jsonl"
    p.write_text(
        json.dumps({"kind": "compile", "tag": "t"}) + "\n"
        + json.dumps({"kind": "warm", "wall_s": 1.0, "sum_s": 2.0}) + "\n"
        + json.dumps({"kind": "warm", "wall_s": 3.0, "sum_s": 4.0}) + "\n")
    rec = gc.load_warm_record(str(p))
    assert rec["wall_s"] == 3.0          # the LAST warm record wins
    p2 = tmp_path / "none.jsonl"
    p2.write_text(json.dumps({"kind": "compile", "tag": "t"}) + "\n")
    assert gc.load_warm_record(str(p2)) is None


# ------------------------------------------------------- bench plumbing
def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_seed_cache_copies_entries(tmp_path, monkeypatch):
    """bench's parent-side seeding is pure file copies (no jax import):
    entries land in the cache dir, existing entries are skipped, pack
    metadata is excluded, and a bad source degrades to a note."""
    bench = _load_bench()
    src = tmp_path / "artifact"
    src.mkdir()
    (src / "abc-cache").write_bytes(b"x" * 64)
    (src / "def-cache").write_bytes(b"y" * 64)
    (src / "MANIFEST.json").write_text("{}")
    (src / ".hidden").write_text("no")
    dst = tmp_path / "cache"
    monkeypatch.setattr(bench, "_CACHE_DIR", str(dst))
    monkeypatch.setenv("BENCH_CACHE_SEED", str(src))
    info = bench._seed_cache()
    assert info["entries_seeded"] == 2 and info["entries_skipped"] == 0
    assert sorted(os.listdir(dst)) == ["abc-cache", "def-cache"]
    # idempotent: a second seed skips everything
    info = bench._seed_cache()
    assert info["entries_seeded"] == 0 and info["entries_skipped"] == 2
    # unset -> no-op; bad dir -> error note, never a raise
    monkeypatch.delenv("BENCH_CACHE_SEED")
    assert bench._seed_cache() is None
    monkeypatch.setenv("BENCH_CACHE_SEED", str(tmp_path / "missing"))
    info = bench._seed_cache()
    assert "error" in info and info["entries_seeded"] == 0


@pytest.mark.heavy
def test_bench_headline_carries_trajectory_and_seed(tmp_path):
    """A full CPU bench run with BENCH_CACHE_SEED: the merged headline
    must carry cache_seeded, the per-attempt compile trajectory, the
    cross-round compile history, and the warm-set keys."""
    src = tmp_path / "artifact"
    src.mkdir()                       # empty artifact: seeded=0 entries
    env = dict(os.environ)
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                "PYTHONUNBUFFERED": "1", "BENCH_1P3B": "0",
                "BENCH_XLA_CACHE": str(tmp_path / "xla_cache"),
                "BENCH_CACHE_SEED": str(src),
                "BENCH_TOTAL_BUDGET": "150"})
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")], env=env,
        timeout=170, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True)
    assert out.returncode == 0
    final = json.loads([l for l in out.stdout.splitlines()
                        if l.startswith("{")][-1])
    assert final["value"] > 0
    assert final["cache_seeded"] is False       # empty artifact
    assert final["cache_seed"]["entries_seeded"] == 0
    assert final["warm_wall_s"] >= 0
    assert final["warm_sum_s"] >= 0
    traj = final["compile_trajectory"]
    assert len(traj) >= 1
    assert traj[0]["attempt"].startswith("scan=1")  # scan-first default
    assert traj[0]["rc"] == "ok"
    assert traj[0]["compile_s"] > 0
    hist = final["compile_history"]
    assert hist[-1]["attempts"][0]["compile_s"] == traj[0]["compile_s"]
    # the trajectory persists across rounds in bench_state.json
    state = json.loads(
        (tmp_path / "xla_cache" / "bench_state.json").read_text())
    assert state["compile_history"][-1]["attempts"][0]["attempt"] \
        == traj[0]["attempt"]


def test_bench_attempt_budget_rolls_over():
    """bench._attempt_budget: a fast first attempt's unused budget
    funds the second attempt past the fixed per-attempt cap, and the
    total-budget fence always wins."""
    bench = _load_bench()
    # attempt 1: plenty of total budget -> the cap, no carry yet
    budget1 = bench._attempt_budget(300.0, 0.0, 500.0)
    assert budget1 == 300.0
    carry = max(0.0, budget1 - 40.0)      # finished in 40s
    # attempt 2: cap + carry, exceeding the old fixed split
    budget2 = bench._attempt_budget(300.0, carry, 460.0)
    assert budget2 == 430.0 > 300.0
    # the 30s merge fence caps everything near the end of the window
    assert bench._attempt_budget(300.0, 260.0, 100.0) == 70.0
