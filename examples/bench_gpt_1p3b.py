"""GPT-1.3B single-chip training benchmark.

A 1.3B-param decoder trains on ONE 16 GB chip: bf16 params (2.6 GB) +
f32 Momentum velocity (5.2 GB) + full activation remat over the scanned
block stack (batch residuals stay [L, B, T, H] bf16). Two caveats this
squeeze accepts, both lifted by sharding over the fleet mesh (ZeRO-1,
distributed.fleet) when more chips are available: AdamW's two f32
moments don't fit, and neither do f32 master weights (multi_precision)
— so per-step updates below a weight's bf16 ulp round away, which a
long real pretraining run should not accept (bench_bert.py shows the
master-weight recipe at a size where it fits).

Measured on a v5e-class chip (seq 1024):
  batch 1: 124 ms/step,  8.2k tokens/s
  batch 4: 336 ms/step, 12.2k tokens/s (~49% nominal MFU)
"""
import json
import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_1p3b, gpt_tiny


def main():
    import jax
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        batch, seq = 4, 1024
        cfg = gpt_1p3b()
        cfg.max_position_embeddings = seq
    else:
        batch, seq = 2, 32
        cfg = gpt_tiny()
    cfg.dropout = 0.0
    cfg.scan_layers = True   # compile the block once, not per layer
    cfg.scan_remat = True    # full recompute: activations stay tiny
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    o = opt.Momentum(learning_rate=1e-4, momentum=0.9,
                     parameters=model.parameters())

    def loss_fn(logits, labels):
        V = logits.shape[-1]
        return nn.functional.cross_entropy(
            logits.reshape([-1, V]), labels.reshape([-1]))

    step = TrainStep(model, loss_fn, o)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    for _ in range(2):
        loss = step(ids, ids)
    float(loss.item())
    iters = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, ids)
    float(loss.item())
    dt = (time.perf_counter() - t0) / iters
    print(json.dumps({
        "n_params": n_params, "batch": batch, "seq": seq,
        "step_ms": round(dt * 1e3, 1),
        "tokens_per_sec": round(batch * seq / dt, 1),
        "loss": round(float(loss.item()), 3)}), flush=True)


if __name__ == "__main__":
    main()
