"""paddlelint — the repo's concurrency + tracing-safety static
analyzer (driver: tools/paddlelint.py, docs: docs/STATIC_ANALYSIS.md).

Five passes over `paddle_tpu/` + `tools/` + `bench.py`, each
mechanizing a bug class the PR 8-12 review-hardening logs kept
finding by hand:

  lock-order             static deadlock detector: cycles in the
                         cross-module lock-acquisition graph
  blocking-under-lock    file I/O / device reads / waits / JSONL
                         export while holding a lock; unbounded
                         explicit acquire()
  unlocked-shared-state  fields mutated on a background thread and
                         read elsewhere with no lock in scope
  use-after-donate       reads of a binding after its buffer was
                         donated to a dispatch
  hot-sync               host syncs inside designated hot regions
                         (tools/check_no_hot_sync.py, migrated — the
                         old CLI is a shim over lint.hot_sync)

Shared engine: tools/lint/core.py (project model, suppression
grammar, baseline ratchet). Known-bad fixture corpora:
tools/lint/fixtures/<pass>/ — each pass must go RED on its own
corpus (tests/test_static_analysis.py enforces it).
"""
from .blocking_under_lock import BlockingUnderLockPass
from .hot_sync import HotSyncPass
from .lock_order import LockOrderPass
from .unlocked_shared_state import UnlockedSharedStatePass
from .use_after_donate import UseAfterDonatePass

#: registration order is report order. blocking-under-lock runs FIRST
#: on purpose: it builds the shared function summaries WITH its effect
#: extractor, and core.build_summaries memoizes that superset for the
#: extractor-less passes behind it — one summary walk per run, not two
ALL_PASSES = (BlockingUnderLockPass, LockOrderPass,
              UnlockedSharedStatePass, UseAfterDonatePass, HotSyncPass)

PASS_NAMES = tuple(p.name for p in ALL_PASSES)

#: the known set a `kind:"lint"` record's `pass` key must come from —
#: the five passes plus the shared suppression engine's meta-pass
#: (core.apply_suppressions emits `suppression-needs-reason` under it)
KNOWN_PASS_NAMES = PASS_NAMES + ("suppression",)


def get_pass(name):
    for cls in ALL_PASSES:
        if cls.name == name:
            return cls()
    raise KeyError(f"unknown lint pass {name!r} (known: "
                   f"{', '.join(PASS_NAMES)})")
