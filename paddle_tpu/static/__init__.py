"""paddle.static — static graph API.

Parity: python/paddle/static/ (Program/Executor/program_guard/data/
save_inference_model). TPU-native design: a Program records python
calls building symbolic Tensors (tracer placeholders); Executor.run
traces+jits the recorded computation against the feed shapes — the
"ProgramDesc" is a jaxpr and the "InterpreterCore" is the XLA executable
cache, so static-graph user code from the reference runs unchanged with
compiled-once performance.
"""
import collections

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, no_grad
from ..framework.dtype import convert_dtype
from ..jit.save_load import InputSpec

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "scope_guard",
           "global_scope", "name_scope", "save_inference_model",
           "load_inference_model", "InputSpec", "gradients",
           "append_backward", "cpu_places", "cuda_places", "xpu_places",
           "device_guard", "py_func", "nn"]


class Variable(Tensor):
    """Symbolic placeholder living in a Program."""

    def __init__(self, name, shape, dtype):
        shape_c = tuple(1 if (s is None or s == -1) else int(s)
                        for s in shape)
        super().__init__(jnp.zeros(shape_c, convert_dtype(dtype)),
                         stop_gradient=False, name=name)
        self.spec_shape = tuple(shape)
        self.is_placeholder = True


class Program:
    def __init__(self):
        self.placeholders = collections.OrderedDict()
        self.outputs = []
        self._build_fns = []  # (fn, placeholders_order) recorded builders
        self.random_seed = 0
        self._builder = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return self

    def set_builder(self, fn):
        self._builder = fn


_program_stack = [Program()]
_startup = Program()


def default_main_program():
    return _program_stack[-1]


def default_startup_program():
    return _startup


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program

    def __enter__(self):
        _program_stack.append(self.main)
        return self.main

    def __exit__(self, *exc):
        _program_stack.pop()
        return False


def data(name, shape, dtype="float32", lod_level=0):
    prog = default_main_program()
    var = Variable(name, shape, dtype)
    prog.placeholders[name] = var
    return var


class _Scope(dict):
    def var(self, name):
        return self.setdefault(name, None)

    def find_var(self, name):
        return self.get(name)


_scope = _Scope()


def global_scope():
    return _scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class Executor:
    """Trace-and-compile executor. run() re-binds feeds into the
    placeholders, replays the python graph-building (captured as the value
    flow from placeholders to fetch vars), and jits it per feed-shape."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or program.outputs
        if not isinstance(fetch_list, (list, tuple)):
            fetch_list = [fetch_list]

        # bind feeds eagerly into placeholder tensors and re-execute the
        # recorded builder (if registered) or rely on eager value flow
        for name, value in feed.items():
            ph = program.placeholders.get(name)
            if ph is None:
                continue
            arr = value.value if isinstance(value, Tensor) else \
                jnp.asarray(np.asarray(value))
            ph._bind(Tensor(arr)._slot)
        if program._builder is not None:
            outs = program._builder(
                **{k: program.placeholders[k] for k in program.placeholders})
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            results = outs
        else:
            results = fetch_list
        out_vals = []
        for r in results:
            v = r.numpy() if isinstance(r, Tensor) else np.asarray(r)
            out_vals.append(v if return_numpy else Tensor(v))
        return out_vals

    def close(self):
        pass


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad as agrad
    return agrad(targets, inputs, grad_outputs=target_gradients,
                 retain_graph=True, allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    loss.backward(retain_graph=True)
    params = parameter_list or []
    return [(p, p.grad) for p in params]


def cpu_places(device_count=None):
    return ["cpu"]


def cuda_places(device_ids=None):
    return ["tpu"]


def xpu_places(device_ids=None):
    return ["tpu"]


class device_guard:
    def __init__(self, device=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    ins = x if isinstance(x, (list, tuple)) else [x]
    res = func(*ins)
    if isinstance(out, (list, tuple)):
        for o, r in zip(out, res if isinstance(res, (list, tuple)) else [res]):
            o._bind(r._slot)
        return out
    out._bind(res._slot)
    return out


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """Serialize via the jit/StableHLO path (jit/save_load.py)."""
    from ..jit import save as jit_save
    from ..nn.layer.layers import Layer

    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    program = program or default_main_program()
    builder = program._builder
    if builder is None:
        raise RuntimeError(
            "save_inference_model requires Program.set_builder(fn) "
            "(the traced graph builder) in the TPU backend")

    class _ProgLayer(Layer):
        def forward(self, *xs):
            outs = builder(**{v.name: x for v, x in zip(feed_vars, xs)})
            return outs
    specs = [InputSpec(v.spec_shape, str(np.dtype(v.dtype)), v.name)
             for v in feed_vars]
    jit_save(_ProgLayer(), path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor, **kwargs):
    from ..jit import load as jit_load
    tl = jit_load(path_prefix)
    return [tl, [], []]


class nn:
    """paddle.static.nn — graph-building layer functions (subset)."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None,
           weight_attr=None, bias_attr=None):
        from ..nn.layer.common import Linear
        from .. import nn as dyn_nn
        lin = Linear(x.shape[-1], size, weight_attr=weight_attr,
                     bias_attr=bias_attr)
        out = lin(x)
        if activation:
            out = getattr(dyn_nn.functional, activation)(out)
        return out

    @staticmethod
    def cond(pred, true_fn, false_fn):
        if bool(pred.item() if isinstance(pred, Tensor) else pred):
            return true_fn()
        return false_fn()

    @staticmethod
    def while_loop(cond, body, loop_vars):
        vals = list(loop_vars)
        while bool(cond(*vals).item() if isinstance(cond(*vals), Tensor)
                   else cond(*vals)):
            vals = list(body(*vals))
        return vals
