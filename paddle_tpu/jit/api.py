"""paddle.jit — trace-and-compile path.

Parity: python/paddle/fluid/dygraph/jit.py + dygraph_to_static/ (the
ProgramTranslator). TPU-native design: instead of AST-rewriting Python into
a ProgramDesc, we *trace* Layer.forward into a jaxpr via a functional view
of the layer (params pytree -> outputs) and hand it to jax.jit — XLA is the
graph program. Python control flow over tensors must use paddle.static.nn
cond/while_loop (lax-backed) exactly as the reference requires graph ops.

`functional_call(layer, params, args)` is the keystone: it temporarily
binds traced arrays into the layer's Parameters so the ordinary eager
forward runs under trace, with the tape disabled (jax.grad provides
differentiation on this path).
"""
import collections
import functools
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, Parameter, no_grad, _Slot
from ..framework.random import rng_scope, split_key
from ..framework import fault_injection as _fault
from ..profiler import statistic as _stat
from ..profiler import monitor as _monitor
from ..profiler import cost as _cost
from ..profiler import flight_recorder as _flight
from ..profiler import compile_observatory as _observatory
from ..profiler import dist_observatory as _dobs
from ..profiler import mem_observatory as _mobs
from .deferred import DeferredLoss
from . import warm as _warm

__all__ = ["functional_call", "to_static", "TrainStep", "not_to_static",
           "aot_compile", "count_train_use", "export_step_metrics",
           "DeferredLoss", "HealthMonitorMixin",
           "CheckpointSnapshotMixin"]

# Tracing binds tracer values into SHARED layer state (_bind swaps
# Parameter slots, dy2static swaps layer.forward, aux-loss records live
# on sublayers) — so two programs over one model must not LOWER
# concurrently, or each trace would read the other's tracers. The warm
# pipeline (jit/warm.py) therefore serializes the trace/lower phase
# under this lock; it costs almost nothing (lowering is GIL-bound
# Python anyway) while the expensive XLA compiles overlap freely on the
# background workers. RLock: a traced forward may re-enter
# functional_call (nested functional layers).
_trace_lock = threading.RLock()


def aot_compile(jitted, args, tag=None, static=None, arg_names=None):
    """Explicitly lower + compile a jax.jit function for `args` — the
    AOT dispatch path TrainStep/HybridTrainStep use instead of jax.jit's
    implicit first-call compile. This is the telemetry keystone: the
    trace/lower and XLA-compile phases get separate host spans
    ("jit.trace_lower", "jit.compile"), the persistent compile cache
    (framework/compile_cache.py) hit/miss is observed (hit = compile
    added no new on-disk entry), and the returned executable exposes
    cost_analysis() for free — no re-lower, no re-compile.

    `tag` names the executable in the flight recorder's registry, so a
    crash/hang debug bundle (profiler/flight_recorder.py) carries its
    HLO text + cost analysis. It is also the compilation observatory's
    key (profiler/compile_observatory.py): every call lands one
    `kind:"compile"` ledger record (lower/compile split, cache hit, HLO
    instruction/fusion counts, bytes/flops, peak-memory estimate), and
    a tag recompiling under a NEW abstract signature emits a structured
    retrace event naming the argument that changed — BEFORE the
    recompile runs, so even a hung compile leaves the diagnosis.

    `static` declares values baked into the traced program rather than
    passed as arrays (run_steps' `n`, accumulate's `k`): they are part
    of the observatory signature so a static-value retrace is named as
    such. `arg_names` labels positional args in forensics output.

    Returns (compiled, info) where info carries lower_s / compile_s /
    cache_hit / flops / bytes. The global jit.* metrics count every
    compile; a train-step object's retraces/compile_s counters advance
    via `count_train_use` only when the executable first runs a
    training step, so inspection compiles (compiled_text / flops on an
    untrained signature) can't fake shape instability.
    """
    from ..framework import compile_cache as _cc
    obs_tag = tag or "aot"
    sig = _observatory.abstract_signature(args, static=static)
    sig_key, _ = _observatory.compile_started(obs_tag, sig,
                                              arg_names=arg_names)
    t0 = time.perf_counter()
    _stat.begin_span("jit.trace_lower")
    try:
        # tracing mutates shared layer state — serialize the lower
        # phase across the warm executor's workers; the XLA compile
        # below runs unlocked (GIL-released C++) and overlaps freely
        with _trace_lock:
            lowered = jitted.lower(*args)
    finally:
        lower_s = _stat.end_span()
    _stat.begin_span("jit.compile")
    try:
        # hit/miss attributed per compile via jax's own per-thread
        # cache events — exact even with concurrent compiles, where a
        # bare entry-set diff would blame one compile's new on-disk
        # entry on another's window
        with _cc.observe_compile() as obs:
            compiled = lowered.compile()
    finally:
        compile_s = _stat.end_span()
    cache_hit = obs.cache_on and obs.cache_hit
    added = obs.entries_added
    total = time.perf_counter() - t0
    _monitor.counter("jit.retraces").inc()
    _monitor.counter("jit.cache_hit" if cache_hit
                     else "jit.cache_miss").inc()
    _monitor.histogram("jit.compile_s").observe(total)
    ca = _cost.cost_analysis(compiled)
    info = {"lower_s": lower_s, "compile_s": compile_s,
            "cache_hit": cache_hit,
            "flops": float(ca.get("flops", 0.0)),
            "bytes": float(ca.get("bytes accessed", 0.0))}
    if tag:  # debug bundles dump this executable's HLO + cost analysis
        _flight.register_executable(tag, compiled)
    _observatory.record_compile(
        obs_tag, sig, sig_key, lower_s, compile_s, cache_hit, compiled,
        cost=ca, arg_names=arg_names, cache_entries_added=len(added))
    return compiled, info


def _step_arg_names(n_batch):
    """Forensics labels for the train-step call signature every
    TrainStep/HybridTrainStep program flavor shares (`_prep` builds the
    matching arg tuple): a retrace event says "batch1: dtype ..."
    instead of "arg8"."""
    return ("params", "opt_state", "scaler_state", "buffers", "key",
            "lr", "step") + tuple(f"batch{i}" for i in range(n_batch))


def count_train_use(owner, info):
    """Fold a compiled executable's cost into the owner's
    retraces/compile_s/last_compile_s the FIRST time it runs a training
    step (idempotent per executable)."""
    if info.get("counted"):
        return
    info["counted"] = True
    total = info["lower_s"] + info["compile_s"]
    owner.retraces += 1
    owner.compile_s += total
    owner.last_compile_s = total


def device_probe_open(step_obj, step_i):
    """Open the cadence-gated device-time probe window for this step,
    or None when the probe is not due (one int modulo —
    dist_observatory.device_probe_due, PADDLE_TPU_DEVICE_TIME_EVERY).
    Opening DRAINS the previous step still in flight, so the window
    that closes after this step's output lands measures THIS step's
    device time — not the async-dispatch pipeline depth. The blocking
    read is the probe's whole point and is explicitly allowlisted; the
    lint fences this function so nothing else creeps in."""
    if not _dobs.device_probe_due(step_i):
        return None
    prev = getattr(step_obj, "_probe_prev_out", None)
    if prev is None:
        return None  # first step: nothing to drain against; next cadence
    t_drain0 = time.perf_counter()
    try:
        jax.block_until_ready(prev)  # hot-sync-ok: cadence-gated device-time probe drain (PADDLE_TPU_DEVICE_TIME_EVERY; docs/OBSERVABILITY.md)
    except (RuntimeError, TypeError):
        return None
    t0 = time.perf_counter()
    # drain_s is the probe's ARTIFICIAL wait: export_step_metrics
    # subtracts it from the probed step's inter-dispatch interval so
    # the step-time accounting keeps real host stalls but not the probe
    return t0, _dobs.eager_wait_s(), t0 - t_drain0


def device_probe_close(step_obj, step_i, window, out_leaf, info,
                       compiled_now=False):
    """Close the probe window: block until this step's output is ready
    and hand the measured wall window to the distributed observatory
    (step_time_device_s / mfu_measured / overlap_fraction — carried in
    this step's record by export_step_metrics). Always stores
    `out_leaf` as the next probe's drain handle; records nothing for a
    step that compiled (the window would measure the compile)."""
    step_obj._probe_prev_out = out_leaf
    if window is None or compiled_now:
        return None
    try:
        jax.block_until_ready(out_leaf)  # hot-sync-ok: cadence-gated device-time probe window close (the ONE deliberate measured sync; lint-fenced)
    except (RuntimeError, TypeError):
        return None
    t0, wait0, drain_s = window
    return _dobs.record_device_time(step_obj, step_i,
                                    time.perf_counter() - t0, info,
                                    coll_wait0=wait0, drain_s=drain_s)


def export_step_metrics(step, dispatch_s, info, compiled_now):
    """Per-step telemetry for a train-step object: step-time histogram,
    cost-analysis FLOPs/MFU gauges, and — when PADDLE_TPU_METRICS_FILE
    is set — one documented JSONL step record
    (tools/check_metrics_schema.py validates the shape).

    step_time_s is the wall time since the previous step's dispatch
    returned: under async dispatch the call itself returns early, but in
    a steady train loop the inter-dispatch interval converges on the
    true device step time. The first (or a recompiling) step falls back
    to its own dispatch time minus the compile."""
    now = time.perf_counter()
    prev = getattr(step, "_last_step_end", None)
    step._last_step_end = now
    compile_s = info["lower_s"] + info["compile_s"] if compiled_now \
        else 0.0
    # the device-time probe (dist_observatory) BLOCKS on the probed
    # step: that step's inter-dispatch interval absorbs the probe's
    # drain wait and the NEXT step's interval collapses to dispatch
    # overhead. The probed step therefore subtracts the measured
    # artificial drain from its interval (real host stalls — a slow
    # data path, an injected delay — stay visible, only the probe's
    # own wait is removed), and the step after a probe is treated like
    # a first step (non-steady: no fake near-zero interval, no absurd
    # MFU from it).
    probe = getattr(step, "_last_device_probe", None)
    if probe is not None and probe.get("step") != int(step._step_i):
        probe = None
    prev_drained = getattr(step, "_probe_drained", False)
    step._probe_drained = probe is not None
    if probe is not None and prev is not None:
        step_time = max(now - prev - probe.get("probe_drain_s", 0.0),
                        0.0)
        steady = True
    else:
        steady = prev is not None and not compiled_now \
            and not prev_drained and probe is None
        if steady:
            step_time = now - prev
        else:
            step_time = max(dispatch_s - compile_s, 0.0)
    flops = float(info.get("flops", 0.0))
    # MFU only from the steady inter-dispatch interval: the fallback
    # dispatch time is near zero under async dispatch and would publish
    # an absurd >1 utilization for the first/recompiling step
    m = _cost.mfu(flops, step_time) if steady else 0.0
    # the step AFTER a probe has no meaningful interval (the probe
    # drained the pipe; its fallback is dispatch overhead) — keep it
    # out of the train.step_s reservoir, which feeds the rankstat
    # p50/p99 the straggler gather compares across ranks
    if not (prev_drained and probe is None):
        _monitor.histogram("train.step_s").observe(step_time)
    _monitor.gauge("train.flops_per_step").set(flops)
    _monitor.gauge("train.bytes_per_step").set(
        float(info.get("bytes", 0.0)))
    _monitor.gauge("train.mfu").set(m)
    # export_step always runs: file or no file, the record lands in the
    # flight-recorder ring so a debug bundle carries the step tail
    from .. import device as _device
    rec = {
        "step": int(step._step_i),
        "step_time_s": float(step_time),
        "compile_s": float(compile_s),
        "cache_hit": bool((not compiled_now) or info["cache_hit"]),
        "peak_bytes": int(_device.max_memory_allocated()),
        "flops": flops,
        "mfu": float(m)}
    # fused-epilogue cost split: epilogue_bytes is the ANALYTIC HBM
    # traffic of the two update passes (ops/pallas/fused_update.py
    # bytes_per_step); epilogue_share relates it to the executable's
    # cost_analysis bytes (clamped — interpret-mode cost analysis counts
    # kernel loop bodies once). The update.epilogue span attributes the
    # same share of the step's wall time for the profiler summary.
    eb = int(getattr(step, "_epilogue_bytes", 0) or 0)
    if eb:
        total_b = float(info.get("bytes", 0.0))
        share = min(eb / total_b, 1.0) if total_b > 0 else 0.0
        rec["epilogue_bytes"] = eb
        rec["epilogue_share"] = float(share)
        _monitor.gauge("train.epilogue_share").set(float(share))
        if steady:
            _stat.record_span("update.epilogue", step_time * share)
    # measured device time (the sampled probe, dist_observatory): the
    # probe that closed on THIS step leaves its numbers here — the
    # step record carries measured time next to the cost-analysis MFU
    if probe is not None:
        rec["step_time_device_s"] = probe["step_time_device_s"]
        rec["mfu_measured"] = probe["mfu_measured"]
        rec["overlap_fraction"] = probe["overlap_fraction"]
    _monitor.export_step(rec)
    # periodic per-rank skew telemetry (kind:"rankstat") — one int
    # modulo off-cadence; emission + the rank-0 peer gather run only at
    # the cadence boundary, never per step
    _dobs.maybe_rankstat(int(step._step_i))
    # periodic device-memory attribution (kind:"memory") — same cadence
    # shape: first step always, then every PADDLE_TPU_MEMORY_EVERY-th
    _mobs.maybe_memory(int(step._step_i), source="train")


def state_arrays(layer):
    """(param_dict, buffer_dict) of raw jax arrays."""
    params = {k: p.value for k, p in layer.named_parameters()}
    buffers = {k: b.value for k, b in layer.named_buffers()}
    return params, buffers


def epilogue_leaf_meta(model, optimizer, params):
    """Per-leaf epilogue metadata from the model's Parameters + the
    optimizer config: need_clip (ClipGradByGlobalNorm opt-out), lr_scale
    (Parameter.optimize_attr), decay-applies (AdamW
    apply_decay_param_fun, keyed by the flat tree name). Returns (meta,
    need_clip_tree, decay_mask_tree, lr_scale_tree) — the tree views are
    None when trivial, so the default config keeps the historical tree
    numerics bit-for-bit; fused and tree paths both consume the SAME
    tables, which is what keeps them numerically equal."""
    named = dict(model.named_parameters())
    meta = {}
    for k in params:
        p = named.get(k)
        attr = getattr(p, "optimize_attr", None)
        meta[k] = {
            "need_clip": bool(getattr(p, "need_clip", True)),
            "lr_scale": float(attr.get("learning_rate", 1.0)) if attr
            else 1.0,
            "decay": bool(optimizer._decay_applies_name(k)),
        }
    nc = {k: m["need_clip"] for k, m in meta.items()}
    dm = {k: m["decay"] for k, m in meta.items()}
    ls = {k: m["lr_scale"] for k, m in meta.items()}
    return (meta,
            None if all(nc.values()) else nc,
            None if all(dm.values()) else dm,
            None if all(v == 1.0 for v in ls.values()) else ls)


def _bind(layer, arrays):
    """Temporarily swap tensor values; returns restore list."""
    saved = []
    named = dict(layer.named_parameters())
    named.update(dict(layer.named_buffers()))
    for k, arr in arrays.items():
        t = named.get(k)
        if t is None:
            continue
        saved.append((t, t._slot))
        t._slot = _Slot(arr)
    return saved


def _restore(saved):
    for t, slot in saved:
        t._slot = slot


def functional_call(layer, params, buffers, args, kwargs=None, rng_key=None,
                    training=None, convert=False):
    """Run layer.forward with the given arrays bound — pure w.r.t. inputs.

    convert=True routes forward through dy2static first, so plain Python
    control flow over tensors lowers onto lax under the trace (the
    to_static / jit.save path)."""
    kwargs = kwargs or {}
    arrays = dict(params)
    arrays.update(buffers)
    conv_prev, conv_had, conv_set = None, False, False
    saved = []
    prev_training = layer.training
    try:
        if convert:
            import types as _types
            from .dy2static import convert_to_static
            # convert may name the specific decorated method (e.g. a
            # @to_static `predict`); True means the layer's forward
            fwd = convert if callable(convert) and convert is not True \
                else type(layer).forward
            # @to_static on the method itself leaves a StaticFunction as
            # the class attribute — unwrap to the underlying function
            if isinstance(fwd, StaticFunction):
                fwd = fwd._obj
            conv = convert_to_static(fwd)
            conv_had = "forward" in layer.__dict__
            conv_prev = layer.__dict__.get("forward")
            layer.__dict__["forward"] = _types.MethodType(conv, layer)
            conv_set = True
        saved = _bind(layer, arrays)
        if training is not None:
            layer.train() if training else layer.eval()
        wrapped_args = [Tensor(a) if not isinstance(a, Tensor) else a
                        for a in args]
        with no_grad():
            if rng_key is not None:
                with rng_scope(rng_key):
                    out = layer(*wrapped_args, **kwargs)
            else:
                out = layer(*wrapped_args, **kwargs)
    finally:
        _restore(saved)
        layer.train() if prev_training else layer.eval()
        if conv_set:
            if conv_had:
                layer.__dict__["forward"] = conv_prev
            else:
                layer.__dict__.pop("forward", None)
    return jax.tree.map(
        lambda t: t.value if isinstance(t, Tensor) else t, out,
        is_leaf=lambda t: isinstance(t, Tensor))


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def reset_aux_losses(model):
    """Drop any stale per-layer auxiliary-loss records (e.g. a tracer
    leaked from a previous trace) before a fresh forward."""
    for layer in model.sublayers(include_self=True):
        if hasattr(layer, "_last_aux"):
            layer._last_aux = None


def collect_aux_losses(model):
    """Sum of `aux_loss_weight * aux` over sublayers that recorded an
    auxiliary loss during the forward just run under the CURRENT trace
    (MoE load-balancing etc.). Returns None when there is none."""
    total = None
    for layer in model.sublayers(include_self=True):
        aux = getattr(layer, "_last_aux", None)
        w = getattr(layer, "aux_loss_weight", 0.0)
        if aux is not None and w:
            a = aux.value if isinstance(aux, Tensor) else aux
            term = w * a
            total = term if total is None else total + term
    return total


class StaticFunction:
    """Compiled wrapper around a Layer or a Tensor function.
    Parity: TranslatedLayer / StaticFunction in the reference."""

    def __init__(self, obj, input_spec=None, build_strategy=None,
                 training=None, method_fn=None):
        self._obj = obj
        self._input_spec = input_spec
        self._training = training
        self._cache = {}
        # when bound via the descriptor protocol: the specific decorated
        # method (may not be `forward`) the compile must execute
        self._method_fn = method_fn
        from ..nn.layer.layers import Layer
        self._is_layer = isinstance(obj, Layer)

    def _sig(self, arrays):
        return tuple((tuple(a.shape), str(a.dtype)) for a in arrays)

    def _compile(self, sig, example_args):
        from .dy2static import convert_to_static
        if self._is_layer:
            layer = self._obj
            training = layer.training if self._training is None \
                else self._training

            # dy2static: convert the forward's Python control flow so
            # tensor-dependent if/while lowers onto lax under the trace
            # (falls back to the original on unsupported constructs)
            conv_target = self._method_fn if self._method_fn is not None \
                else True

            def pure(params, buffers, key, *xs):
                return functional_call(layer, params, buffers, xs,
                                       rng_key=key, training=training,
                                       convert=conv_target)
            jitted = jax.jit(pure)
        else:
            fn = convert_to_static(self._obj)

            def pure(key, *xs):
                with no_grad(), rng_scope(key):
                    out = fn(*[Tensor(x) for x in xs])
                return jax.tree.map(
                    lambda t: t.value if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            jitted = jax.jit(pure)
        self._cache[sig] = jitted
        return jitted

    def __call__(self, *args, **kwargs):
        from ..framework.core import apply_op, is_grad_enabled
        arrays = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        sig = self._sig(arrays)
        jitted = self._cache.get(sig)
        new_program = jitted is None
        if new_program:
            jitted = self._compile(sig, arrays)
            _monitor.counter("jit.retraces").inc()
        key = split_key()
        if self._is_layer:
            named = list(self._obj.named_parameters())
            buffers = {k: b.value for k, b in self._obj.named_buffers()}
            # train-through-to_static (reference StaticFunction records
            # grads): when the tape is live, run the jitted program AS a
            # taped op over the Parameters + inputs so loss.backward()
            # reaches them; jax.vjp differentiates through jax.jit
            if is_grad_enabled() and any(
                    not p.stop_gradient for _, p in named):
                names = [k for k, _ in named]
                n = len(names)

                def fn(*flat, _names=tuple(names), _n=n, _j=jitted,
                       _b=buffers, _k=key):
                    pd = dict(zip(_names, flat[:_n]))
                    return _j(pd, _b, _k, *flat[_n:])

                tensor_args = [a if isinstance(a, Tensor) else Tensor(a)
                               for a in args]
                return apply_op(fn, *[p for _, p in named], *tensor_args)
            params = {k: p.value for k, p in named}
            t0 = time.perf_counter()
            out = jitted(params, buffers, key, *arrays)
        else:
            t0 = time.perf_counter()
            out = jitted(key, *arrays)
        if new_program:
            # jax.jit compiles lazily on this first dispatch; the elapsed
            # time is trace+compile (dispatch returns right after compile
            # under async execution)
            _stat.record_span("jit.compile", time.perf_counter() - t0)
        return jax.tree.map(Tensor, out)

    def __get__(self, instance, owner=None):
        """Descriptor protocol: `@to_static` directly on a method (the
        reference's most common idiom) must bind like a method. Accessed
        through an instance we return a per-layer StaticFunction that
        compiles through the functional layer path."""
        if instance is None:
            return self
        name = getattr(self._obj, "__name__", "forward")
        key = f"_jit_static_{name}"
        bound = instance.__dict__.get(key)
        if bound is None:
            bound = StaticFunction(instance, self._input_spec, None,
                                   self._training, method_fn=self._obj)
            instance.__dict__[key] = bound
            if name == "forward":  # jit.save looks here for spec inference
                instance.__dict__["_jit_static_forward"] = bound
        return bound

    # Layer-protocol passthroughs so a converted layer still acts like one
    def __getattr__(self, name):
        return getattr(self._obj, name)

    @property
    def forward(self):
        return self.__call__

    def concrete_program(self):
        return self._cache

    @property
    def wrapped(self):
        return self._obj


def to_static(layer_or_function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """paddle.jit.to_static: decorator or call. Compiles via jax.jit."""
    def wrap(obj):
        if getattr(obj, "_not_to_static", False):
            return obj
        return StaticFunction(obj, input_spec, build_strategy)
    if layer_or_function is None:
        return wrap
    return wrap(layer_or_function)


class HealthMonitorMixin:
    """Host half of the in-graph training-health observatory, shared by
    TrainStep and HybridTrainStep (`monitor_health=True`).

    The in-graph half appends `_health_vec` — ONE tiny f32 vector of
    [loss, grad_norm, param_norm, update_ratio, found_inf] — to the
    already-compiled step. The host half here starts an async D2H copy
    at dispatch and folds vectors into the detectors only once they have
    LANDED (is_ready-gated): zero new host syncs on the hot path.
    `flush_health()` is the blocking drain (epoch end, tests)."""

    def _init_health(self, monitor_health):
        self.monitor_health = bool(monitor_health)
        self._health_pending = collections.deque()
        self.last_health = None
        if self.monitor_health:
            from ..profiler.health import AnomalyDetector
            self.anomalies = AnomalyDetector()
        else:
            self.anomalies = None

    def _health_vec(self, loss, aux):
        """[loss, grad_norm, param_norm, update_ratio, found_inf] as ONE
        f32 device vector, computed under the trace (monitor_health=True
        appends this to the compiled step). `aux` is `_finish`'s
        epilogue by-product dict: the grad norm is computed ONCE per
        step (shared with the clip factor and — via the GradScaler or
        non-finiteness — found_inf), never as a second tree traversal;
        the fused epilogue's pass-2 kernels supply param/update sums as
        per-chunk side accumulators."""
        grad_norm = aux["grad_norm"]
        # found_inf preference order: the GradScaler's exact flag, then
        # the epilogue's full-tree non-finite sweep (covers leaves a
        # need_clip mask keeps out of the norm), then norm finiteness
        found = aux.get("found_inf")
        if found is None:
            found = aux.get("nonfinite")
        found_inf = found.astype(jnp.float32) if found is not None \
            else (~jnp.isfinite(grad_norm)).astype(jnp.float32)
        param_norm = jnp.sqrt(aux["param_sumsq"])
        update_ratio = jnp.sqrt(aux["update_sumsq"]) / jnp.maximum(
            param_norm, 1e-12)
        return jnp.stack([loss.astype(jnp.float32).reshape(()), grad_norm,
                          param_norm, update_ratio, found_inf])

    @staticmethod
    def _tree_health_aux(aux, params, new_params):
        """Fill aux's param/update sums for a TREE-layout epilogue (the
        fused path's kernels produce them as side outputs instead)."""
        def sumsq(tree):
            leaves = [jnp.sum(jnp.square(l.astype(jnp.float32)))
                      for l in jax.tree.leaves(tree)]
            total = leaves[0] if leaves else jnp.zeros((), jnp.float32)
            for l in leaves[1:]:
                total = total + l
            return total

        aux["param_sumsq"] = sumsq(new_params)
        delta = jax.tree.map(
            lambda n, o: n.astype(jnp.float32) - o.astype(jnp.float32),
            new_params, params)
        aux["update_sumsq"] = sumsq(delta)
        return aux

    def _queue_health(self, step_i, vec):
        """Start the async D2H copy of one step's health vector, then
        fold any vectors that have ALREADY landed into the detectors.
        Never blocks the step loop — resolution is is_ready-gated;
        `flush_health()` is the blocking drain."""
        try:
            vec.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass  # non-jax array or backend without async copy
        self._health_pending.append((step_i, vec))
        self._drain_health(block=False)

    def _drain_health(self, block):
        while self._health_pending:
            step_i, vec = self._health_pending[0]
            if not block:
                ready = getattr(vec, "is_ready", None)
                if ready is not None and not ready():
                    return  # still computing/copying: check next step
            self._health_pending.popleft()
            self._observe_health(step_i, vec)

    def _observe_health(self, step_i, vec):
        vals = [float(v) for v in np.asarray(vec)]  # hot-sync-ok: vector already landed (is_ready-gated or explicit flush)
        h = dict(zip(("loss", "grad_norm", "param_norm", "update_ratio",
                      "found_inf"), vals))
        self.last_health = {"step": int(step_i), **h}
        _monitor.gauge("health.grad_norm").set(h["grad_norm"])
        _monitor.gauge("health.update_ratio").set(h["update_ratio"])
        # JSONL strictness: a bare NaN token is not valid JSON — export
        # non-finite values as their repr strings (the anomaly event
        # carries the signal; tools/check_metrics_schema.py accepts both)
        import math as _math
        rec = {k: (v if _math.isfinite(v) else repr(v))
               for k, v in h.items()}
        rec["step"] = int(step_i)
        _monitor.export_step(rec, kind="health")
        if self.anomalies is not None:
            self.anomalies.observe(step_i, h, retraces=self.retraces)

    def flush_health(self):
        """Blocking drain of the pending health vectors (epoch end,
        shutdown, tests). Returns the most recent resolved health dict
        (`{"step", "loss", "grad_norm", "param_norm", "update_ratio",
        "found_inf"}`) or None when monitor_health is off / no step ran."""
        self._drain_health(block=True)
        return self.last_health


class CheckpointSnapshotMixin:
    """The checkpoint surface TrainStep and HybridTrainStep share —
    what `distributed.checkpoint.CheckpointManager` saves and restores.

    `tree_state()` is the canonical state tree: per-leaf params and
    optimizer-state VIEWS plus the GradScaler's jit state ({} when no
    scaler rides the step). `snapshot_state()` returns ON-DEVICE buffer
    copies of that tree: the copies are dispatched asynchronously (the
    host returns immediately) and are detached from the donated
    buffers, so the step loop can keep dispatching while the
    checkpoint writer streams the snapshot to disk — the core of the
    snapshot-then-write save path (docs/FAULT_TOLERANCE.md). The
    restore inverse is `set_tree_state` (layout-aware on both the
    fused-flat-store and hybrid-sharded layouts) plus a `scaler_state`
    assignment."""

    def tree_state(self):
        return {"params": self.params,
                "opt_state": self.opt_state,
                "scaler_state": self.scaler_state}

    def snapshot_state(self):
        return jax.tree.map(jnp.copy, self.tree_state())


def fire_step_faults(step_obj, batch):
    """The `train.step` fault-injection site every train-step dispatch
    passes through (framework/fault_injection.py): hard actions
    (kill-at-step-k, delay) execute inside fire(); the soft `nan`
    action is implemented here by NaN-filling the first floating batch
    leaf, so the whole gradient goes non-finite (the GradScaler /
    health path must catch it); the soft `oom` action arms a flag the
    dispatch raises as a synthetic RESOURCE_EXHAUSTED from inside its
    real try-block, so the memory observatory's forensics path runs
    end-to-end. Returns the (possibly poisoned) batch."""
    acts = _fault.fire("train.step")
    if not acts:
        return batch
    if "oom" in acts:
        step_obj._oom_fault = True
    if "nan" not in acts:
        return batch
    out = list(batch)
    for i, b in enumerate(out):
        v = b.value if isinstance(b, Tensor) else jnp.asarray(b)
        if jnp.issubdtype(v.dtype, jnp.floating):
            poisoned = jnp.full_like(v, jnp.nan)
            out[i] = Tensor(poisoned) if isinstance(b, Tensor) \
                else poisoned
            return tuple(out)
    raise ValueError(
        "nan@train.step fault needs at least one floating-point batch "
        "input to poison (integer-id models: inject at the loss level "
        "or use a float-input model in the drill)")


class TrainStep(HealthMonitorMixin, CheckpointSnapshotMixin):
    """One fully-jitted training step: forward + loss + grads + optimizer.

    The TPU-native analogue of the reference's whole-program executor path:
    everything — including the optimizer update and (with `scaler=`) the
    GradScaler's dynamic loss scaling — is a single XLA computation;
    parameter, optimizer-state, and scaler-state buffers are DONATED so
    XLA aliases input/output buffers and updates in place in HBM instead
    of holding a second full copy of the model per step.

        step = TrainStep(model, loss_fn, optimizer)
        loss = step(x, y)          # DeferredLoss: dispatch returns early
        float(loss)                # first host read blocks (recorded)
        step.sync_to_model()       # copy back into Parameters when needed

    The returned loss is a `DeferredLoss` (still a Tensor): the host only
    blocks when the value is actually read, so a steady train loop issues
    step k+1 while step k computes. `accumulate(k, ...)` folds k
    microbatches into one scanned update; `run_steps(n, ...)` scans whole
    optimizer steps.

    Compile observability (the warm-start contract the persistent compile
    cache in framework/compile_cache.py is measured by):
        step.retraces        # how many distinct programs were compiled
        step.compile_s       # total seconds spent tracing+compiling
        step.last_compile_s  # the most recent compile, seconds
    """

    def __init__(self, model, loss_fn, optimizer, mesh=None,
                 in_shardings=None, donate=True, model_returns_loss=False,
                 scaler=None, monitor_health=False, fused_update=None):
        """model_returns_loss=True: the model's forward(*batch) IS the
        scalar loss (e.g. GPTForCausalLM.fused_loss via a wrapper) —
        loss_fn is ignored. Lets memory-fused loss formulations (chunked
        vocab xent) run under the same jitted step.

        scaler: an amp.GradScaler whose dynamic loss scaling runs INSIDE
        the compiled step (scaled loss, unscale, found_inf update skip,
        scale adaptation) with its state donated alongside params.

        monitor_health=True: the compiled step additionally computes the
        training-health scalars — loss, global grad norm, param norm,
        update ratio, found_inf — INSIDE the already-fused XLA program
        (a handful of reductions next to terms XLA already computes) and
        returns them as one tiny f32 vector on the DeferredLoss-style
        async path: the host starts a D2H copy at dispatch and folds the
        vector into `self.anomalies` (profiler/health.AnomalyDetector)
        only once it has LANDED (is_ready-gated — zero new host syncs on
        the hot path; `flush_health()` is the blocking drain). Each
        resolved step also exports a `kind:"health"` metrics record.
        Donation and GradScaler semantics are unchanged.

        fused_update: run the optimizer epilogue as the fused
        multi-tensor Pallas kernels over dtype-bucketed flat buffers
        (ops/pallas/fused_update.py) instead of the per-leaf tree op
        chain. Default (None) reads PADDLE_TPU_FUSED_UPDATE (on unless
        "0") and silently falls back to the tree path when the
        optimizer/clip config has no fused mapping (Lars, RMSProp,
        per-leaf ClipGradByNorm, stochastic rounding). Both paths are
        numerically equal (tests/test_fused_update.py); params and
        opt_state remain visible as per-leaf tree VIEWS either way."""
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.scaler = scaler
        self._model_returns_loss = model_returns_loss
        params, self.buffers = state_arrays(model)
        # params are donated every step; take a private copy so the
        # model's own Parameters stay valid for eager use
        params = jax.tree.map(jnp.array, params)
        self._collect_leaf_meta(model, optimizer, params)
        self._fused = self._build_fused(params, fused_update)
        if self._fused is not None:
            self._params_store, self._opt_store = self._fused.init_stores(
                params, optimizer._multi_precision)
        else:
            self._params_store = params
            self._opt_store = jax.tree.map(
                lambda v: self.optimizer.init_leaf_state(v), params,
                is_leaf=lambda x: hasattr(x, "dtype"))
        # an empty dict is a valid (leafless) donated pytree when no
        # scaler rides along, keeping one step_fn signature
        self.scaler_state = scaler.init_jit_state() if scaler is not None \
            else {}
        # memory-observatory attribution: the stores are donated and
        # REPLACED every step, so register getters (weakref to self)
        # that read the current trees at report time
        _mobs.register("params",
                       self, lambda s: jax.tree.leaves(s._params_store))
        _mobs.register("opt_state",
                       self, lambda s: jax.tree.leaves(s._opt_store))
        self._oom_fault = False
        self._step_i = 0
        self._mesh = mesh
        self.retraces = 0
        self.compile_s = 0.0
        self.last_compile_s = None
        self._init_health(monitor_health)
        if self._fused is not None:
            from ..nn.clip import ClipGradByGlobalNorm
            self._epilogue_bytes = self._fused.bytes_per_step(
                scaling=scaler is not None and scaler.is_enable(),
                need_norm=bool(monitor_health) or isinstance(
                    optimizer._grad_clip, ClipGradByGlobalNorm),
                master_keys=set(self._opt_store["masters"]))

        def step_fn(params, opt_state, scaler_state, buffers, key, lr,
                    step_i, *batch):
            loss, grads = jax.value_and_grad(
                lambda ps: self._objective(ps, scaler_state, buffers, key,
                                           batch))(params)
            loss, new_params, new_state, new_scaler, _ = self._finish(
                loss, grads, params, opt_state, scaler_state, lr, step_i)
            return loss, new_params, new_state, new_scaler

        def step_fn_health(params, opt_state, scaler_state, buffers, key,
                           lr, step_i, *batch):
            loss, grads = jax.value_and_grad(
                lambda ps: self._objective(ps, scaler_state, buffers, key,
                                           batch))(params)
            out_loss, new_params, new_state, new_scaler, aux = \
                self._finish(loss, grads, params, opt_state, scaler_state,
                             lr, step_i, want_health=True)
            health = self._health_vec(out_loss, aux)
            return out_loss, health, new_params, new_state, new_scaler

        donate_argnums = (0, 1, 2) if donate else ()
        self._donate = donate
        # the plain flavor stays: run_steps scans it (the scanned path
        # keeps the 4-tuple carry; health rides the per-step programs)
        self._step_fn = step_fn
        self._jitted = jax.jit(
            step_fn_health if self.monitor_health else step_fn,
            donate_argnums=donate_argnums)
        # AOT executables keyed by batch signature (aot_compile): phases
        # timed, persistent-cache hit observed, cost_analysis free
        self._exec = {}
        self._scan_jit = {}
        self._acc_jit = {}

    # -- fused epilogue plumbing ----------------------------------------
    def _collect_leaf_meta(self, model, optimizer, params):
        (self._leaf_meta, self._need_clip_tree, self._decay_mask_tree,
         self._lr_scale_tree) = epilogue_leaf_meta(model, optimizer,
                                                   params)

    def _build_fused(self, params, fused_update):
        """The fused multi-tensor epilogue for this (optimizer, clip,
        params) config, or None -> per-leaf tree path. Explicit
        fused_update=True/False wins over PADDLE_TPU_FUSED_UPDATE."""
        import os
        if fused_update is None:
            fused_update = os.environ.get(
                "PADDLE_TPU_FUSED_UPDATE", "1") != "0"
        if not fused_update or not params:
            return None
        spec = self.optimizer.fused_spec()
        if spec is None:
            return None
        from ..nn.clip import ClipGradByGlobalNorm, ClipGradByValue
        clip = self.optimizer._grad_clip
        if clip is not None and not isinstance(
                clip, (ClipGradByGlobalNorm, ClipGradByValue)):
            return None
        if not all(jnp.issubdtype(v.dtype, jnp.floating)
                   for v in jax.tree.leaves(params)):
            return None
        from ..ops.pallas.fused_update import BucketLayout, FusedEpilogue
        layout = BucketLayout(
            [(k, v.shape, v.dtype) for k, v in params.items()],
            meta=self._leaf_meta)
        return FusedEpilogue(layout, spec)

    @property
    def params(self):
        """Per-leaf {name: array} view of the step's parameters. On the
        fused path the donated truth lives in dtype-bucketed flat
        buffers (`_params_store`); this view slices them back out."""
        if self._fused is not None:
            return self._fused.layout.unpack(self._params_store)
        return self._params_store

    @property
    def opt_state(self):
        """Per-leaf optimizer-state view ({name: tuple | {"master",
        "state"}}), state_dict-compatible on both epilogue layouts."""
        if self._fused is not None:
            return self._fused.state_view(self._opt_store)
        return self._opt_store

    def set_tree_state(self, params=None, opt_state=None):
        """Load per-leaf state back into the step (checkpoint restore:
        distributed/checkpoint.load_train_state) — the layout-aware
        inverse of the `params`/`opt_state` views, packing into the
        donated flat stores on the fused path."""
        if params is not None:
            self._params_store = self._fused.layout.pack(params) \
                if self._fused is not None \
                else {k: jnp.asarray(v) for k, v in params.items()}
        if opt_state is not None:
            self._opt_store = self._fused.pack_opt_tree(opt_state) \
                if self._fused is not None else opt_state

    # -- traced pieces (shared by __call__ / run_steps / accumulate) -----
    def _loss_of(self, ps, buffers, key, batch):
        """Scalar training loss of one (micro)batch under the trace."""
        model, loss_fn = self.model, self.loss_fn
        reset_aux_losses(model)
        if self._model_returns_loss:
            out = functional_call(model, ps, buffers, batch,
                                  rng_key=key, training=True)
            l = out.value if isinstance(out, Tensor) else out
        else:
            out = functional_call(model, ps, buffers, batch[:-1],
                                  rng_key=key, training=True)
            tgt = Tensor(batch[-1])
            loss_t = loss_fn(
                out if isinstance(out, Tensor) else Tensor(out), tgt)
            l = loss_t.value if isinstance(loss_t, Tensor) else loss_t
        aux = collect_aux_losses(model)
        return l if aux is None else l + aux.astype(l.dtype)

    def _objective(self, ps, scaler_state, buffers, key, batch):
        """The differentiated quantity: the loss, scaled when a
        GradScaler rides inside the step. `ps` is the donated parameter
        store — on the fused path the dtype-bucketed flat buffers, whose
        per-leaf views the forward consumes (differentiating THROUGH the
        unpack makes the gradients arrive already bucketed: the VJP
        packs leaf cotangents with one concatenate per bucket)."""
        if self._fused is not None:
            ps = self._fused.layout.unpack(ps)
        l = self._loss_of(ps, buffers, key, batch)
        if self.scaler is not None and self.scaler.is_enable():
            return l.astype(jnp.float32) * scaler_state["scale"]
        return l

    def _finish(self, loss, grads, params, opt_state, scaler_state, lr,
                step_i, want_health=False):
        """From (possibly scaled) loss + grads to the updated carry: one
        unscale/scale-adaptation, one clip, ONE optimizer update —
        whether the grads came from one batch or a scanned accumulation
        of k microbatches. Returns (loss, new_params, new_state,
        new_scaler_state, aux); aux carries the epilogue's shared
        by-products — the ONE global grad norm (clip factor, health
        grad_norm) and found_inf — plus the health sums when
        want_health.

        Fused path: two Pallas passes over the flat buffers
        (ops/pallas/fused_update.py). Tree path: the per-leaf reference
        shape, with the grad norm computed ONCE and threaded to both
        the clip and the health vector instead of per-consumer."""
        scaler = self.scaler
        clip = self.optimizer._grad_clip
        if self._fused is not None:
            if scaler is not None and scaler.is_enable():
                loss = loss / scaler_state["scale"]
            new_params, new_state, new_scaler_state, aux = \
                self._fused.finish(
                    grads, params, opt_state, lr, step_i, scaler=scaler,
                    scaler_state=scaler_state, clip=clip,
                    with_stats=want_health)
            return loss, new_params, new_state, new_scaler_state, aux
        if scaler is not None and scaler.is_enable():
            loss = loss / scaler_state["scale"]
            grads, found_inf, new_scaler_state = \
                scaler.jit_unscale_and_update(scaler_state, grads)
        else:
            found_inf, new_scaler_state = None, scaler_state
        from ..nn.clip import (clip_grads_tree, global_grad_norm,
                               ClipGradByGlobalNorm)
        gn = None
        if want_health or isinstance(clip, ClipGradByGlobalNorm):
            gn = global_grad_norm(grads, self._need_clip_tree)
        grads = clip_grads_tree(grads, clip,
                                need_clip=self._need_clip_tree,
                                global_norm=gn)
        new_params, new_state = self.optimizer.apply_gradients_tree(
            params, grads, opt_state, lr, step_i, found_inf=found_inf,
            decay_mask=self._decay_mask_tree,
            lr_scale=self._lr_scale_tree)
        aux = {"grad_norm": gn, "found_inf": found_inf}
        if want_health:
            self._tree_health_aux(aux, params, new_params)
            if gn is not None:
                nonfin = ~jnp.isfinite(gn)
                if self._need_clip_tree is not None:
                    # leaves a need_clip mask keeps out of the norm must
                    # still trip the health found_inf signal
                    for k, g in grads.items():
                        if not self._need_clip_tree.get(k, True):
                            nonfin = nonfin | jnp.any(~jnp.isfinite(
                                g.astype(jnp.float32)))
                aux["nonfinite"] = nonfin
        return loss, new_params, new_state, new_scaler_state, aux

    def _dispatch(self, cache, sig, make_jitted, args, span,
                  max_entries=None, static=None, arg_names=None):
        """The ONE dispatch path every TrainStep program flavor
        (per-step / scanned steps / scanned accumulation) goes through:
        executable-cache lookup with optional LRU bound, AOT compile on
        miss, retrace accounting, timed dispatch. `static`/`arg_names`
        feed the compilation observatory's signature + forensics.

        A miss goes through the warm pipeline's single-flight table
        (jit/warm.py): if `warm()`/`warm_run_steps()`/`warm_accumulate()`
        already has this executable compiling in the background, the
        dispatch JOINS that compile — blocking only on the one
        executable it actually needs, never duplicating the work or the
        ledger record. Returns (outputs, info, compiled_now,
        dispatch_s)."""
        _flight.heartbeat(self._step_i)  # watchdog liveness pulse
        _stat.begin_span(span)
        try:
            entry = cache.get(sig)
            compiled_now = entry is None
            if compiled_now:
                if max_entries and len(cache) >= max_entries:
                    cache.pop(next(iter(cache)))  # bound compile growth
                # inline=True: a dispatch miss compiles on THIS thread
                # when it wins the single-flight race — never queued
                # behind unrelated background warms; if a warm already
                # has this executable in flight, join it instead
                entry = self._warm_submit(
                    cache, sig, make_jitted, span, args, static=static,
                    arg_names=arg_names, inline=True).result()
            else:  # LRU: re-insert so cycling signatures don't thrash
                cache[sig] = cache.pop(sig)
            compiled, info = entry
            count_train_use(self, info)
            try:
                if getattr(self, "_oom_fault", False):
                    # oom@train.step soft fault: raise the synthetic
                    # exhaustion from INSIDE the real dispatch try so
                    # the forensics below is the tested path
                    self._oom_fault = False
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: injected OOM "
                        "(oom@train.step fault): failed to allocate "
                        "request for 8.00GiB on device")
                out = compiled(*args)
            except (FloatingPointError, RuntimeError) as e:
                if _mobs.is_oom(e):
                    # allocator exhaustion: dump mem_state.json forensics
                    # and re-raise naming the top holders
                    raise _mobs.oom_error(e, site=span) from e
                # jax_debug_nans (framework.debug.enable_jit_nan_checks)
                # found a non-finite value: flight-record it and write a
                # debug bundle (ring tail + this executable's HLO +
                # all-thread stacks) before re-raising to the caller.
                # With donated buffers the op-level re-run cannot replay
                # (inputs already consumed) and surfaces as a
                # RuntimeError over deleted arrays — same detection,
                # reported as the FloatingPointError it is.
                donated_rerun = (
                    isinstance(e, RuntimeError)
                    and jax.config.jax_debug_nans
                    and "deleted" in str(e))
                if isinstance(e, RuntimeError) and not donated_rerun:
                    raise
                _flight.record_event("nan_detected", where=span,
                                     step=int(self._step_i),
                                     error=str(e)[:300])
                _flight.dump("nan", exc=e)
                if donated_rerun:
                    raise FloatingPointError(
                        "jax_debug_nans detected a non-finite value in "
                        f"the compiled {span} program (the op-level "
                        "re-run could not localize it because the step "
                        "donates its buffers; build with donate=False "
                        "to localize)") from e
                raise
        finally:
            dispatch_s = _stat.end_span()
        return out, info, compiled_now, dispatch_s

    def _prep_run_steps(self, n, batch, data_per_step):
        """(sig, make_jitted, static, arrays) for one scanned-steps
        program — the ONE place run_steps' signature and program factory
        are built, shared by `run_steps` and `warm_run_steps` so a
        warmed executable is exactly the one dispatch will use."""
        arrays = [b.value if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
        if data_per_step:
            for a in arrays:
                # ndim check first: a 0-d scalar has no shape[0] and must
                # hit this friendly error, not an IndexError
                if a.ndim == 0 or a.shape[0] != n:
                    raise ValueError(
                        f"data_per_step=True needs a leading dim of n={n} "
                        f"on every batch array, got shape {a.shape} — a "
                        "traced gather would silently clamp short arrays "
                        "to their last micro-batch")
        # NOTE: n (and the batch shapes) are static — each distinct
        # signature compiles its own scanned program, kept in a small
        # cache; prefer a fixed segment length plus a per-step tail
        sig = (n, bool(data_per_step),
               tuple((a.shape, str(a.dtype)) for a in arrays))

        def make_jitted():
            step_fn = self._step_fn

            def multi(params, opt_state, scaler_state, buffers, key, lr,
                      base, *arrs):
                def body(carry, i):
                    p, s, sc = carry
                    b = [a[i] for a in arrs] if data_per_step else list(arrs)
                    # step index as f32: `beta ** step` with a traced int
                    # promotes to f64 under x64, breaking the scan carry
                    loss, p, s, sc = step_fn(
                        p, s, sc, buffers, jax.random.fold_in(key, i), lr,
                        (base + i).astype(jnp.float32), *b)
                    return (p, s, sc), loss

                (p, s, sc), losses = jax.lax.scan(
                    body, (params, opt_state, scaler_state),
                    jnp.arange(n, dtype=jnp.int32))
                return losses, p, s, sc

            return jax.jit(
                multi, donate_argnums=(0, 1, 2) if self._donate else ())

        static = {"n": n, "data_per_step": bool(data_per_step)}
        return sig, make_jitted, static, arrays

    def _run_steps_args(self, arrays):
        key = split_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        base = jnp.asarray(self._step_i + 1, jnp.int32)
        return (self._params_store, self._opt_store, self.scaler_state,
                self.buffers, key, lr, base, *arrays)

    def run_steps(self, n, *batch, data_per_step=False):
        """Run `n` optimizer steps in ONE XLA dispatch (lax.scan over the
        step body) and return the per-step losses as a Tensor of shape [n].

        The TPU-native analogue of the reference executor running many
        iterations per `Executor.run` call (ref python/paddle/fluid/
        executor.py): the whole loop lives on device, so per-step host
        dispatch (and, under a remote/tunneled TPU, per-step round-trip
        latency) disappears. Best for small/host-bound models. For models
        whose params+optimizer state dominate HBM, per-step `__call__`
        with buffer donation can be faster: XLA double-buffers a while-
        loop carry, where donated per-dispatch buffers update in place
        (measured 3.3x on the 355M-param bench config). With `data_per_step=True` every batch array
        carries a leading `n` dimension holding one micro-batch per step;
        otherwise the same batch is reused each step (benchmarking/
        overfit-sanity loops). The learning rate is frozen at its current
        scheduler value for the scanned segment; call `scheduler.step()`
        between segments for piecewise schedules."""
        sig, make_jitted, static, arrays = self._prep_run_steps(
            n, batch, data_per_step)
        args = self._run_steps_args(arrays)
        out, info, compiled_now, dt = self._dispatch(
            self._scan_jit, sig, make_jitted, args, "train.run_steps",
            max_entries=8, static=static,
            arg_names=_step_arg_names(len(arrays)))
        losses, self._params_store, self._opt_store, \
            self.scaler_state = out
        # telemetry keeps dispatch-only time: the first call's span also
        # covered the compile
        if compiled_now:
            dt = max(dt - (info["lower_s"] + info["compile_s"]), 0.0)
        _monitor.histogram("train.run_steps_s").observe(dt)
        _monitor.export_step(
            {"steps": n,
             "dispatch_s": float(dt),  # hot-sync-ok: host perf counter
             "flops": float(  # hot-sync-ok: python dict value, not device
                 info.get("flops", 0.0))}, kind="scan")
        self._step_i += n
        return Tensor(losses)

    def _make_acc_fn(self, k):
        """The scanned-microbatch accumulation program: k microbatches
        folded with ONE optimizer update (reuses the same traced pieces
        as the per-step path, so GradScaler/clip/donation semantics are
        identical)."""
        def acc_fn(params, opt_state, scaler_state, buffers, key, lr,
                   step_i, *batch):
            def body(carry, xs):
                i, micro = xs[0], xs[1:]
                loss_sum, grads_sum = carry
                l, g = jax.value_and_grad(
                    lambda ps: self._objective(
                        ps, scaler_state, buffers,
                        jax.random.fold_in(key, i), micro))(params)
                return (loss_sum + l.astype(jnp.float32),
                        jax.tree.map(jnp.add, grads_sum, g)), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            (loss_sum, grads), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zeros),
                (jnp.arange(k, dtype=jnp.int32), *batch))
            # mean over microbatches: for mean-reduced losses this makes
            # the update numerically identical to one k-times-larger
            # batch (equal microbatch sizes)
            loss = loss_sum / k
            grads = jax.tree.map(lambda g: g / k, grads)
            out_loss, new_params, new_state, new_scaler, aux = \
                self._finish(loss, grads, params, opt_state, scaler_state,
                             lr, step_i, want_health=self.monitor_health)
            if self.monitor_health:
                health = self._health_vec(out_loss, aux)
                return out_loss, health, new_params, new_state, new_scaler
            return out_loss, new_params, new_state, new_scaler
        return acc_fn

    def _prep_accumulate(self, k, batch):
        """(sig, make_jitted, arrays) for one scanned-accumulation
        program — shared by `accumulate` and `warm_accumulate` so the
        warmed executable is exactly the one dispatch will use."""
        arrays = [b.value if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
        for a in arrays:
            if a.ndim == 0 or a.shape[0] != k:
                raise ValueError(
                    f"accumulate(k={k}) needs a leading microbatch dim of "
                    f"{k} on every batch array, got shape {a.shape}")
        sig = (k, tuple((a.shape, str(a.dtype)) for a in arrays))

        def make_jitted():
            return jax.jit(
                self._make_acc_fn(k),
                donate_argnums=(0, 1, 2) if self._donate else ())

        return sig, make_jitted, arrays

    def accumulate(self, k, *batch):
        """ONE optimizer update from `k` scanned microbatches in ONE XLA
        dispatch. Every batch array carries a leading dim of `k` (one
        microbatch per slot); gradients are averaged across microbatches
        inside the scan, then the usual unscale/clip/update runs exactly
        once — numerics match a single step over the k-times-larger batch
        for mean-reduced losses, with only one microbatch's activations
        live at a time. Params/opt/scaler state stay donated. This is
        what `hapi.Model.fit(accumulate_grad_batches=k)` dispatches."""
        sig, make_jitted, arrays = self._prep_accumulate(k, batch)
        if k == 1:
            return self(*[a[0] for a in arrays])
        self._step_i += 1
        key = split_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        args = (self._params_store, self._opt_store, self.scaler_state,
                self.buffers, key, lr, self._step_i, *arrays)

        out, info, compiled_now, dispatch_s = self._dispatch(
            self._acc_jit, sig, make_jitted, args, "train.accumulate",
            max_entries=8, static={"k": k},
            arg_names=_step_arg_names(len(arrays)))
        if self.monitor_health:
            loss, health, self._params_store, self._opt_store, \
                self.scaler_state = out
            self._queue_health(self._step_i, health)
        else:
            loss, self._params_store, self._opt_store, \
                self.scaler_state = out
        export_step_metrics(self, dispatch_s, info, compiled_now)
        return DeferredLoss(loss)

    def input_sharding(self, arr):
        """Sharding the compiled step expects for a batch leaf — the
        device prefetch ring (io/device_prefetch.py) asks this so H2D
        copies land placed for the step while the previous step computes.
        The single-device step has no placement constraint (None =
        default device)."""
        return None

    def _prep(self, batch, step_i):
        """(sig, full arg tuple) for one dispatch — the ONE place the
        call signature is built: __call__ and the inspection paths must
        agree exactly, because the cached executable bakes the input
        avals."""
        arrays = [b.value if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        args = (self._params_store, self._opt_store, self.scaler_state,
                self.buffers, split_key(),
                jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                step_i, *arrays)
        return sig, args

    # -- background warmup (the compile pipeline, jit/warm.py) -----------
    def _warm_submit(self, cache, sig, make_jitted, tag, args,
                     static=None, arg_names=None, inline=False):
        """Single-flight compile of one executable (warm.submit_cached):
        background for warm() calls, `inline=True` for dispatch-path
        misses (the caller needs this executable NOW and must not queue
        behind unrelated background warms); either way a racer joins
        the one flight, and the entry installs into `cache` before the
        flight closes."""
        return _warm.submit_cached(
            cache, sig, tag,
            lambda: aot_compile(make_jitted(), args, tag=tag,
                                static=static, arg_names=arg_names),
            inline=inline)

    def warm(self, *batch):
        """Start a BACKGROUND AOT compile of the per-step executable for
        exactly this batch signature and return a `jit.warm.WarmHandle`
        — the host keeps doing useful work (building data pipelines,
        warming OTHER executables) while XLA compiles on a worker
        thread; the first `__call__` with this signature joins the
        in-flight compile instead of recompiling. Because the signature
        comes from the same `_prep` as dispatch (same shapes, dtypes,
        shardings, donation), warming adds ZERO executables beyond the
        steady-state set — provable from the compilation observatory's
        ledger. Join a whole warm set with `jit.warm.join(handles)`,
        which also records the wall-vs-sum overlap evidence."""
        sig, args = self._prep(batch, self._step_i + 1)
        return self._warm_submit(self._exec, sig, lambda: self._jitted,
                                 "train.step", args,
                                 arg_names=_step_arg_names(len(batch)))

    def warm_run_steps(self, n, *batch, data_per_step=False):
        """Background-compile the `run_steps(n, ...)` scanned program
        for this signature (see `warm`)."""
        sig, make_jitted, static, arrays = self._prep_run_steps(
            n, batch, data_per_step)
        args = self._run_steps_args(arrays)
        return self._warm_submit(self._scan_jit, sig, make_jitted,
                                 "train.run_steps", args, static=static,
                                 arg_names=_step_arg_names(len(arrays)))

    def warm_accumulate(self, k, *batch):
        """Background-compile the `accumulate(k, ...)` scanned program
        for this signature (see `warm`). k == 1 warms the per-step
        executable, mirroring the dispatch path."""
        sig, make_jitted, arrays = self._prep_accumulate(k, batch)
        if k == 1:
            return self.warm(*[a[0] for a in arrays])
        args = (self._params_store, self._opt_store, self.scaler_state,
                self.buffers, split_key(),
                jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                self._step_i + 1, *arrays)
        return self._warm_submit(self._acc_jit, sig, make_jitted,
                                 "train.accumulate", args,
                                 static={"k": k},
                                 arg_names=_step_arg_names(len(arrays)))

    def __call__(self, *batch):
        self._step_i += 1
        if _fault.active():  # fault drills only; two dict reads when off
            batch = fire_step_faults(self, batch)
        sig, args = self._prep(batch, self._step_i)
        probe = device_probe_open(self, self._step_i)
        out, info, compiled_now, dispatch_s = self._dispatch(
            self._exec, sig, lambda: self._jitted, args, "train.step",
            arg_names=_step_arg_names(len(batch)))
        if self.monitor_health:
            loss, health, self._params_store, self._opt_store, \
                self.scaler_state = out
            self._queue_health(self._step_i, health)
        else:
            loss, self._params_store, self._opt_store, \
                self.scaler_state = out
        device_probe_close(self, self._step_i, probe, loss, info,
                           compiled_now=compiled_now)
        export_step_metrics(self, dispatch_s, info, compiled_now)
        # non-blocking handle: dispatch has already returned; the host
        # copy streams in the background and resolves on first read
        return DeferredLoss(loss)

    def cost_analysis(self, *batch):
        """XLA's analytical cost report for THIS batch signature's
        per-step executable ({'flops', 'bytes accessed', ...}) — free
        when the step has already run (the AOT executable is cached);
        otherwise compiles it first (warm via the persistent cache)
        without touching the retrace counters."""
        return _cost.cost_analysis(self._executable(*batch))

    def flops(self, *batch):
        """Per-step FLOPs of the compiled executable (0.0 unknown)."""
        return _cost.executable_flops(self._executable(*batch))

    def _executable(self, *batch):
        sig, args = self._prep(batch, self._step_i + 1)
        entry = self._exec.get(sig)
        if entry is None:
            # single-flight with any in-flight warm of this signature
            entry = self._warm_submit(
                self._exec, sig, lambda: self._jitted, "train.step",
                args, arg_names=_step_arg_names(len(batch)),
                inline=True).result()
        return entry[0]

    def sync_to_model(self):
        named = dict(self.model.named_parameters())
        with no_grad():
            for k, v in self.params.items():
                named[k]._slot = _Slot(v)
        if self.scaler is not None and self.scaler_state:
            self.scaler.sync_from_jit_state(self.scaler_state)

    def compiled_text(self, *batch):
        """Optimized HLO of the per-step executable (inspection/tests:
        the donation proof greps input_output_alias entries here).
        Reuses the AOT executable cache — no extra compile after a
        step has run with this signature."""
        return self._executable(*batch).as_text()
