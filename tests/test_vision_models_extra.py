"""Model families added for full vision parity: DenseNet, ResNeXt,
GoogLeNet, InceptionV3, ShuffleNetV2 scale variants — plus hub/sysconfig/
onnx and the Bilinear initializer."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models

pytestmark = [pytest.mark.slow, pytest.mark.heavy]  # multi-minute: out of tier-1 and the quick gate


def _img(n=1, c=3, hw=64):
    rng = np.random.RandomState(0)
    return paddle.to_tensor(
        rng.randn(n, c, hw, hw).astype(np.float32))


@pytest.mark.heavy
def test_resnext_forward_and_width():
    m = models.resnext50_32x4d(num_classes=10)
    m.eval()
    out = m(_img())
    assert tuple(out.shape) == (1, 10)
    # 32x4d channel plan: stage-1 grouped conv width 128
    assert m.layer1[0].conv2.weight.shape[0] == 128
    m64 = models.ResNeXt(depth=50, cardinality=64, num_classes=10)
    assert m64.layer1[0].conv2.weight.shape[0] == 256


@pytest.mark.heavy
def test_densenet_forward():
    m = models.densenet121(num_classes=10)
    m.eval()
    out = m(_img())
    assert tuple(out.shape) == (1, 10)
    # growth plan: 121 ends at 1024 features
    assert m.fc.weight.shape[0] == 1024
    assert models.DenseNet(layers=161, num_classes=10).fc.weight.shape[0] \
        == 2208


@pytest.mark.heavy
def test_googlenet_three_outputs():
    m = models.googlenet(num_classes=10)
    m.eval()
    out, aux1, aux2 = m(_img())
    assert tuple(out.shape) == (1, 10)
    assert tuple(aux1.shape) == (1, 10)
    assert tuple(aux2.shape) == (1, 10)


@pytest.mark.heavy
def test_inception_v3_forward():
    m = models.inception_v3(num_classes=10)
    m.eval()
    out = m(_img(hw=96))
    assert tuple(out.shape) == (1, 10)


@pytest.mark.heavy
def test_shufflenet_variants():
    for fn, last in [(models.shufflenet_v2_x0_25, 512),
                     (models.shufflenet_v2_swish, 1024)]:
        m = fn(num_classes=10)
        m.eval()
        assert tuple(m(_img(hw=64)).shape) == (1, 10)
        assert m.fc.weight.shape[0] == last


def test_bilinear_initializer():
    import paddle_tpu.nn as nn
    from paddle_tpu.nn.initializer import Bilinear
    conv = nn.Conv2DTranspose(2, 2, 4, stride=2,
                              weight_attr=paddle.ParamAttr(
                                  initializer=Bilinear()))
    w = np.asarray(conv.weight.numpy())
    k1d = np.array([0.25, 0.75, 0.75, 0.25], dtype=np.float32)
    expect = np.outer(k1d, k1d)
    np.testing.assert_allclose(w[0, 0], expect, atol=1e-6)
    np.testing.assert_allclose(w[1, 1], expect, atol=1e-6)


def test_hub_local(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['numpy']\n"
        "def tiny_model(scale=2):\n"
        "    '''doc of tiny_model'''\n"
        "    return scale * 21\n")
    assert paddle.hub.list(str(tmp_path), source="local") == ["tiny_model"]
    assert "doc of tiny_model" in paddle.hub.help(
        str(tmp_path), "tiny_model", source="local")
    assert paddle.hub.load(str(tmp_path), "tiny_model",
                           source="local", scale=2) == 42
    with pytest.raises(NotImplementedError):
        paddle.hub.load("owner/repo", "m", source="github")


def test_sysconfig_and_onnx():
    import os
    assert os.path.isdir(paddle.sysconfig.get_include())
    with pytest.raises(NotImplementedError):
        paddle.onnx.export(None, "model")
