"""paddle.dataset.mnist — legacy reader-creator API over the idx-gzip
parser in paddle_tpu.vision.datasets.MNIST.

Parity: /root/reference/python/paddle/dataset/mnist.py (samples are
(float32[784] scaled to [-1, 1], int label)).
"""
import numpy as np

from ..vision.datasets import MNIST

__all__ = []


def _reader_creator(mode):
    def reader():
        ds = MNIST(mode=mode)
        images = ds.images.reshape(len(ds), -1).astype(np.float32)
        images = images / 255.0 * 2.0 - 1.0
        for img, label in zip(images, ds.labels):
            yield img, int(label)

    return reader


def train():
    """MNIST training set creator: 60k (image[784] in [-1,1], label)."""
    return _reader_creator("train")


def test():
    """MNIST test set creator: 10k (image[784] in [-1,1], label)."""
    return _reader_creator("test")


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/mnist/train-images-idx3-ubyte.gz",
             "mnist", None)
