"""Fault-tolerant training: snapshot-then-write async checkpointing,
atomic commits, verified resume, and the fault-injection harness
(docs/FAULT_TOLERANCE.md).

The headline drill is subprocess kill-and-resume: train under
ElasticController, SIGKILL the process MID-ASYNC-SAVE via an injected
fault (`kill@ckpt.commit#2` / `kill@ckpt.write#15`), relaunch, and
assert the continuation is BIT-IDENTICAL (sha256 over every state
leaf: params + opt state + scaler + step counter) to an uninterrupted
run — on both the TrainStep and HybridTrainStep (dp/mp mesh) paths.
The calibrated overlap test proves the async save is off the critical
path: an injected 0.8 s write delay must not stretch the step loop.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest
import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.framework import fault_injection as fi
from paddle_tpu.jit import TrainStep
from paddle_tpu.distributed.checkpoint import (CheckpointManager,
                                               COMMIT_NAME,
                                               MANIFEST_NAME)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_ckpt_worker.py")
TOOLS = os.path.join(REPO, "tools")


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No fault spec may leak across tests (or in from the env)."""
    os.environ.pop("PADDLE_TPU_FAULT_SPEC", None)
    fi.configure("")
    yield
    os.environ.pop("PADDLE_TPU_FAULT_SPEC", None)
    fi.configure("")


def _loss_fn(out, y):
    return paddle.mean(paddle.nn.functional.square_error_cost(out, y))


def _build_step(seed=0, **kw):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters())
    return TrainStep(m, _loss_fn, o, **kw)


def _batch(n=16):
    rs = np.random.RandomState(0)
    return (paddle.to_tensor(rs.randn(n, 8).astype("float32")),
            paddle.to_tensor(rs.randn(n, 1).astype("float32")))


# ---------------------------------------------------------------- spec

def test_fault_spec_parsing():
    faults = fi.parse_spec(
        "kill@ckpt.write#2; eio@ckpt.write, delay@ckpt.serialize=0.25,"
        "truncate@ckpt.write=100; nan@train.step#3")
    assert [(f.action, f.site, f.nth, f.arg) for f in faults] == [
        ("kill", "ckpt.write", 2, None),
        ("eio", "ckpt.write", None, None),
        ("delay", "ckpt.serialize", None, 0.25),
        ("truncate", "ckpt.write", None, 100),
        ("nan", "train.step", 3, None)]
    for bad in ("frob@x", "kill@", "killckpt", "kill@x#0"):
        with pytest.raises(ValueError):
            fi.parse_spec(bad)


def test_fault_fire_counts_and_eio(tmp_path):
    fi.configure("eio@t.site#2")
    assert fi.fire("t.site") is None          # hit 1: no match
    with pytest.raises(OSError):
        fi.fire("t.site")                     # hit 2: injected EIO
    assert fi.fire("t.site") is None          # hit 3: past the match
    assert fi.hit_counts()["t.site"] == 3
    fi.configure("nan@t.soft")
    assert fi.fire("t.soft") == ["nan"]       # soft: reported, not run


# ----------------------------------------------------- save + restore

def test_checkpoint_roundtrip_and_commit_layout(tmp_path):
    step = _build_step()
    x, y = _batch()
    for _ in range(3):
        float(step(x, y))
    mgr = CheckpointManager(str(tmp_path), keep_last=3)
    handle = mgr.save(step)
    path = handle.result(60)
    rec = handle.record
    assert rec["committed"] and rec["bytes"] > 0 and rec["n_leaves"] >= 12
    assert rec["snapshot_s"] + rec["serialize_s"] + rec["write_s"] + \
        rec["commit_s"] <= rec["total_s"] + 1e-3
    # commit protocol on disk: manifest + COMMIT marker, no temp dirs
    assert os.path.isfile(os.path.join(path, MANIFEST_NAME))
    assert os.path.isfile(os.path.join(path, COMMIT_NAME))
    assert not [d for d in os.listdir(tmp_path) if d.startswith(".tmp-")]
    cont = [float(step(x, y)) for _ in range(2)]

    fresh = _build_step(seed=123)   # different init: must be overwritten
    restored = CheckpointManager(str(tmp_path)).restore(fresh)
    assert restored == 3 and fresh._step_i == 3
    assert [float(fresh(x, y)) for _ in range(2)] == cont
    for k in step.params:
        np.testing.assert_array_equal(np.asarray(step.params[k]),
                                      np.asarray(fresh.params[k]))
    mgr.close()


def test_restore_falls_back_past_corrupt_and_truncated(tmp_path):
    step = _build_step()
    x, y = _batch()
    mgr = CheckpointManager(str(tmp_path), keep_last=5)
    float(step(x, y)), float(step(x, y))
    mgr.save(step).result(60)              # step 2 (good)
    float(step(x, y)), float(step(x, y))
    mgr.save(step).result(60)              # step 4 (will be truncated)
    float(step(x, y)), float(step(x, y))
    p6 = mgr.save(step).result(60)         # step 6 (will be corrupted)
    float(step(x, y)), float(step(x, y))
    p8 = mgr.save(step).result(60)         # step 8 (byte-flipped)

    # damage: truncate a shard of step 4, garbage the manifest of 6,
    # and flip one byte (same size — only the checksum can tell) in 8
    p4 = os.path.join(tmp_path, "step_00000004")
    shard = os.path.join(p4, sorted(
        f for f in os.listdir(p4) if f.startswith("shard_"))[0])
    with open(shard, "r+b") as f:
        f.truncate(os.path.getsize(shard) // 2)
    with open(os.path.join(p6, MANIFEST_NAME), "w") as f:
        f.write("{not json")
    shard8 = os.path.join(p8, "shard_00000.bin")
    with open(shard8, "r+b") as f:
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    ok4, problem4, _ = mgr.verify(p4)
    assert not ok4 and "truncated" in problem4
    ok6, problem6, _ = mgr.verify(p6)
    assert not ok6
    ok8, problem8, _ = mgr.verify(p8)      # full-crc verify catches it
    assert not ok8 and "checksum" in problem8

    fresh = _build_step(seed=9)
    m2 = CheckpointManager(str(tmp_path))
    restored = m2.restore(fresh)
    assert restored == 2, "must fall back past ALL damaged checkpoints"
    assert m2.last_restore_record["fell_back"] == 3
    assert m2.last_restore_record["verified"] is True
    mgr.close()


def test_uncommitted_dir_is_skipped(tmp_path):
    """A step_N dir without a COMMIT marker (non-atomic copy, torn
    publish) must not be restorable."""
    step = _build_step()
    x, y = _batch()
    float(step(x, y)), float(step(x, y))
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(step).result(60)
    float(step(x, y)), float(step(x, y))
    p4 = mgr.save(step).result(60)
    os.remove(os.path.join(p4, COMMIT_NAME))
    fresh = _build_step(seed=5)
    assert CheckpointManager(str(tmp_path)).restore(fresh) == 2
    mgr.close()


def test_injected_eio_fails_save_but_not_the_manager(tmp_path):
    step = _build_step()
    x, y = _batch()
    float(step(x, y))
    mgr = CheckpointManager(str(tmp_path))
    fi.configure("eio@ckpt.write#1")
    h = mgr.save(step)
    with pytest.raises(OSError):
        h.result(60)
    assert h.record["committed"] is False
    assert mgr.all_steps() == []
    assert not [d for d in os.listdir(tmp_path)
                if d.startswith(".tmp-")], "failed save must clean up"
    fi.configure("")
    assert mgr.save(step).result(60)       # manager still functional
    assert mgr.all_steps() == [1]
    mgr.close()


def test_retention_gc_keep_last_and_keep_every(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=2, keep_every=4)
    w = {"w": paddle.to_tensor(np.ones(4, np.float32)).value}
    for s in (2, 4, 6, 8, 10):
        mgr.save(w, step=s).result(60)
    # keep_last=2 -> {8, 10}; keep_every=4 -> {4, 8}
    assert mgr.all_steps() == [4, 8, 10]
    mgr.close()


def test_plain_dict_tree_restores_in_place(tmp_path):
    """save()/restore() of a bare pytree (no train step): the dict is
    restored IN PLACE, not silently left at its pre-restore values."""
    mgr = CheckpointManager(str(tmp_path))
    tree = {"a": paddle.to_tensor(np.arange(4, dtype=np.float32)).value,
            "b": {"c": paddle.to_tensor(
                np.ones((2, 2), np.float32)).value}}
    mgr.save(tree, step=3).result(60)
    import jax.numpy as jnp
    mutated = {"a": jnp.zeros(4, jnp.float32),
               "b": {"c": jnp.full((2, 2), 7.0, jnp.float32)}}
    assert CheckpointManager(str(tmp_path)).restore(mutated) == 3
    np.testing.assert_array_equal(np.asarray(mutated["a"]),
                                  np.arange(4, dtype=np.float32))
    np.testing.assert_array_equal(np.asarray(mutated["b"]["c"]),
                                  np.ones((2, 2), np.float32))
    mgr.close()


def test_latest_ignores_nonconforming_names(tmp_path):
    """Satellite: stray files / step_123.tmp / partials must not crash
    the newest-checkpoint scan."""
    mgr = CheckpointManager(str(tmp_path))
    os.makedirs(tmp_path / "step_123.tmp")
    os.makedirs(tmp_path / ".tmp-step_00000099-partial")
    (tmp_path / "stray.txt").write_text("x")
    os.makedirs(tmp_path / "step_00000007")   # committed-looking name,
    assert mgr.all_steps() == [7]             # (verify() rejects it)
    assert mgr.latest().endswith("step_00000007")
    step = _build_step()
    assert mgr.restore(step) is None          # unverifiable: skipped


# ------------------------------------------------ async overlap proof

def test_async_save_off_the_critical_path(tmp_path):
    """Calibrated: with an injected 0.8 s delay in the WRITE phase, the
    step loop dispatched during the background write must finish in a
    fraction of that — and the record's snapshot phase must be an
    order of magnitude shorter than its write phase."""
    step = _build_step()
    x, y = _batch()
    for _ in range(3):
        float(step(x, y))
    mgr = CheckpointManager(str(tmp_path), keep_last=2)
    mgr.save(step).result(60)       # warm the snapshot/copy programs
    fi.configure("delay@ckpt.write#1=0.8")
    t0 = time.perf_counter()
    h = mgr.save(step)
    enqueue_s = time.perf_counter() - t0
    losses = [step(x, y) for _ in range(6)]
    float(losses[-1])               # resolve: all 6 steps done
    loop_s = time.perf_counter() - t0
    rec_path = h.result(60)
    fi.configure("")
    rec = h.record
    assert rec_path and rec["committed"]
    assert rec["write_s"] >= 0.8, rec
    assert enqueue_s < 0.4, f"save() blocked the caller: {enqueue_s}"
    assert loop_s < 0.56, \
        f"step loop waited on the background write: {loop_s:.3f}s " \
        f"vs write_s {rec['write_s']:.3f}s"
    assert rec["snapshot_s"] * 10 <= rec["write_s"], rec
    mgr.close()


# ----------------------------------------------- telemetry + schema

def test_ckpt_records_validate_and_trace_track(tmp_path):
    mfile = tmp_path / "metrics.jsonl"
    os.environ["PADDLE_TPU_METRICS_FILE"] = str(mfile)
    try:
        step = _build_step()
        x, y = _batch()
        float(step(x, y)), float(step(x, y))
        mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=1)
        mgr.save(step).result(60)
        float(step(x, y)), float(step(x, y))
        mgr.save(step).result(60)            # triggers GC of step 2
        fresh = _build_step(seed=3)
        CheckpointManager(str(tmp_path / "ck")).restore(fresh)
        mgr.close()
    finally:
        os.environ.pop("PADDLE_TPU_METRICS_FILE", None)

    sys.path.insert(0, TOOLS)
    try:
        import check_metrics_schema as cms
    finally:
        sys.path.pop(0)
    assert cms.validate_file(str(mfile)) == []
    recs = [json.loads(l) for l in mfile.read_text().splitlines() if l]
    ckpt = [r for r in recs if r.get("kind") == "ckpt"]
    ops = [r["op"] for r in ckpt]
    assert ops.count("save") == 2 and "restore" in ops and "gc" in ops
    restore_rec = [r for r in ckpt if r["op"] == "restore"][-1]
    assert restore_rec["verified"] is True and restore_rec["step"] == 4

    # the Perfetto "checkpoint" track renders the records
    from paddle_tpu.profiler import trace_export
    tf = trace_export.write_chrome_trace(str(tmp_path / "trace.json"))
    payload = json.load(open(tf))
    names = [e.get("name") for e in payload["traceEvents"]]
    assert "checkpoint" in [
        e["args"]["name"] for e in payload["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"]
    assert any(n and n.startswith("ckpt save step") for n in names)
    assert cms.validate_file(tf) == []


def test_nan_injection_trips_scaler_and_health():
    """nan@train.step poisons a float batch leaf -> the whole gradient
    goes non-finite -> the in-step GradScaler skips the update and the
    health vector reports found_inf."""
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 8)
    step = TrainStep(m, _loss_fn, o, scaler=scaler, monitor_health=True)
    x, y = _batch()
    fi.configure("nan@train.step#2")         # hits count from here
    float(step(x, y))                        # hit 1: clean
    before = {k: np.asarray(v) for k, v in step.params.items()}
    float(step(x, y))                        # hit 2: poisoned step
    fi.configure("")
    h = step.flush_health()
    assert h["step"] == 2 and h["found_inf"] == 1.0
    for k, v in step.params.items():         # found_inf: update skipped
        np.testing.assert_array_equal(before[k], np.asarray(v))


def test_watchdog_dumps_bundle_with_ckpt_state_before_sigterm(tmp_path):
    from paddle_tpu.distributed.elastic import ElasticController
    fired = []
    prev = signal.signal(signal.SIGTERM, lambda *a: fired.append(True))
    os.environ["PADDLE_TPU_DEBUG_DUMP"] = str(tmp_path / "dump")
    try:
        step = _build_step()
        ctl = ElasticController(step, str(tmp_path / "ck"),
                                watchdog_timeout_s=0.4)
        ctl.start_watchdog()
        deadline = time.time() + 10
        while not fired and time.time() < deadline:
            time.sleep(0.05)
        ctl.stop()
        assert fired, "watchdog did not fire on a stalled step loop"
        bundle = tmp_path / "dump" / "elastic_watchdog"
        assert (bundle / "MANIFEST.json").is_file()
        state = json.load(open(bundle / "ckpt_state.json"))
        assert state["directory"] == str(tmp_path / "ck")
        assert state["committed_steps"] == []
    finally:
        os.environ.pop("PADDLE_TPU_DEBUG_DUMP", None)
        signal.signal(signal.SIGTERM, prev)


# --------------------------------------------- hybrid sharded resume

def _hybrid_mlp_step(seed):
    from paddle_tpu.distributed import fleet

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc_in = nn.Linear(16, 32)    # P(None, 'mp')
            self.fc_out = nn.Linear(32, 8)    # P('mp', None)
            self.act = nn.Tanh()

        def forward(self, x):
            return self.fc_out(self.act(self.fc_in(x)))

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = 2
    strategy.hybrid_configs["mp_degree"] = 2
    strategy.hybrid_configs["sharding_degree"] = 2
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    m = MLP()
    o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters())
    return fleet.build_train_step(m, _loss_fn, o)


def test_sharded_roundtrip_lands_in_placement(tmp_path):
    """Satellite: load_train_state must pass the shardings it builds,
    so a dp/mp (+ZeRO) resume restores each array DIRECTLY into its
    distributed placement — and the CheckpointManager path must match."""
    from paddle_tpu.distributed.checkpoint import (save_train_state,
                                                   load_train_state)
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(16, 16).astype("float32"))
    y = paddle.to_tensor(rs.randn(16, 8).astype("float32"))
    step = _hybrid_mlp_step(0)
    for _ in range(2):
        float(step(x, y))
    assert "mp" in str(step.params["fc_in.weight"].sharding.spec)
    save_train_state(step, str(tmp_path / "orbax"))
    mgr = CheckpointManager(str(tmp_path / "native"))
    mgr.save(step).result(60)
    cont = [float(step(x, y)) for _ in range(2)]

    for flavor in ("orbax", "native"):
        fresh = _hybrid_mlp_step(seed=42)
        if flavor == "orbax":
            load_train_state(fresh, str(tmp_path / "orbax"))
        else:
            assert CheckpointManager(
                str(tmp_path / "native")).restore(fresh) == 2
        assert fresh._step_i == 2
        # arrays landed in their dp/mp/ZeRO placement, not unsharded
        assert fresh.params["fc_in.weight"].sharding == \
            step.param_shardings["fc_in.weight"], flavor
        opt_leaf = jax.tree.leaves(fresh.opt_state["fc_in.weight"])[0]
        assert "sharding" in str(opt_leaf.sharding.spec), \
            (flavor, opt_leaf.sharding)
        assert [float(fresh(x, y)) for _ in range(2)] == cont, flavor
    mgr.close()


# --------------------------------------------------- hapi fit resume

def test_model_fit_resume_continues_step_counter(tmp_path):
    from paddle_tpu.hapi.model import Model

    def make():
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
        model = Model(net)
        model.prepare(
            optimizer=opt.AdamW(learning_rate=1e-2,
                                parameters=net.parameters()),
            loss=_loss_fn)
        return model

    rs = np.random.RandomState(0)
    data = [(rs.randn(4).astype("float32"),
             rs.randn(1).astype("float32")) for _ in range(8)]
    ckdir = str(tmp_path / "fit_ck")

    model = make()
    model.fit(data, batch_size=4, epochs=2, verbose=0, shuffle=False,
              resume=ckdir)
    assert model._train_step._step_i == 4       # 2 epochs x 2 updates
    mgr = CheckpointManager(ckdir)
    assert mgr.all_steps(), "fit must have committed a checkpoint"

    resumed = make()
    resumed.fit(data, batch_size=4, epochs=1, verbose=0, shuffle=False,
                resume=ckdir)
    # restored at step 4, then one more epoch of 2 updates
    assert resumed._train_step._step_i == 6


# --------------------------------------- kill-and-resume (subprocess)

def _run_worker(flavor, target, ckpt, out, fault=None, expect_rc=0,
                save_every=2):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["CKPT_SAVE_EVERY"] = str(save_every)
    env.pop("PADDLE_TPU_FAULT_SPEC", None)
    env.pop("PADDLE_TPU_METRICS_FILE", None)
    if fault:
        env["PADDLE_TPU_FAULT_SPEC"] = fault
    p = subprocess.run(
        [sys.executable, WORKER, flavor, str(target), str(ckpt),
         str(out)],
        env=env, cwd=REPO, capture_output=True, timeout=300)
    assert p.returncode == expect_rc, \
        f"rc={p.returncode} (expected {expect_rc})\n" \
        f"{p.stdout.decode()[-2000:]}\n{p.stderr.decode()[-2000:]}"


@pytest.mark.heavy
@pytest.mark.parametrize("flavor,fault", [
    # die at the START of the 2nd checkpoint's commit (pre-rename):
    # shards + manifest written, never published
    ("single", "kill@ckpt.commit#2"),
    # die while streaming the 2nd checkpoint's shard files (the first
    # save writes 12 shards, so hit 15 is mid-second-write)
    ("hybrid", "kill@ckpt.write#15"),
])
def test_kill_mid_save_then_resume_bit_identical(tmp_path, flavor,
                                                 fault):
    """SIGKILL mid-async-save -> relaunch -> resume from the last
    COMMITTED checkpoint (partial temp dir skipped and GC'd) ->
    continuation bit-identical to an uninterrupted run (params + opt
    state + scaler + step counter, via sha256 digest)."""
    base_out = tmp_path / "baseline.json"
    res_out = tmp_path / "resumed.json"
    ckpt = tmp_path / "ckpt"

    # 1. uninterrupted baseline to step 8
    _run_worker(flavor, 8, tmp_path / "ckpt_base", base_out)
    baseline = json.load(open(base_out))
    assert baseline["start"] == 0 and baseline["step"] == 8

    # 2. train under the controller; the injected fault SIGKILLs the
    #    process while the background writer saves a checkpoint
    _run_worker(flavor, 8, ckpt, tmp_path / "unused.json",
                fault=fault, expect_rc=-signal.SIGKILL)
    committed = [d for d in os.listdir(ckpt) if d.startswith("step_")]
    partials = [d for d in os.listdir(ckpt) if d.startswith(".tmp-")]
    assert committed, "at least one checkpoint must have committed"
    assert partials, \
        "the kill mid-save must leave a partial temp dir behind"

    # 3. relaunch: resume past the partial, finish the run
    _run_worker(flavor, 8, ckpt, res_out)
    resumed = json.load(open(res_out))
    assert resumed["start"] > 0, "must resume from a committed step"
    assert resumed["step"] == 8
    assert not [d for d in os.listdir(ckpt) if d.startswith(".tmp-")], \
        "resume must GC the partial temp dir"

    # 4. bit-identical continuation: every replayed loss equal, and the
    #    full final state digest equal to the uninterrupted run's
    for s, loss in resumed["losses"].items():
        assert baseline["losses"][s] == loss, (s, flavor)
    assert resumed["digest"] == baseline["digest"], \
        "resumed state is not bit-identical to the uninterrupted run"
