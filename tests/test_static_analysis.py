"""paddlelint — the unified concurrency + tracing-safety static
analyzer (ISSUE 14, tools/lint/ + tools/paddlelint.py,
docs/STATIC_ANALYSIS.md).

Proof points:
- every pass is GREEN on HEAD (zero unsuppressed findings over the
  real fileset) and RED on its known-bad fixture corpus
  (tools/lint/fixtures/<pass>/), naming file:line and the violated
  rule;
- the suppression engine: `# lint-ok[pass]: <why>` suppresses exactly
  its line/pass, a marker WITHOUT a reason is itself a finding, and
  suppressed findings still reach the kind:"lint" ledger with their
  reasons;
- the baseline ratchet refuses to loosen: suppressed-count growth
  fails the gate, `--update` only ever writes counts DOWN;
- `tools/check_no_hot_sync.py` stays a byte-compatible shim over the
  hot-sync pass (same verdict strings, same exit codes — the
  pre-existing lint tests in test_async_pipeline.py and friends run
  unchanged on top);
- `kind:"lint"` records validate against tools/check_metrics_schema.py
  (pass from the known set, file:line present, severity enum,
  suppressed => non-empty reason) and the schema tool's pass set never
  drifts from the framework's;
- tools/obs_report.py renders the findings section.

All host-side source analysis — no device work; runs in tier-1.
"""
import importlib.util
import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
FIXTURES = os.path.join(TOOLS, "lint", "fixtures")

if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import paddlelint  # noqa: E402
from lint import ALL_PASSES, KNOWN_PASS_NAMES, PASS_NAMES, core  # noqa: E402


def _load_tool(name):
    path = os.path.join(TOOLS, name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _no_session_ledger(monkeypatch):
    """The driver appends findings to PADDLE_TPU_METRICS_FILE when set
    (the canonical-workload contract) — keep these runs out of
    whatever ledger the surrounding test session configured."""
    monkeypatch.delenv("PADDLE_TPU_METRICS_FILE", raising=False)


@pytest.fixture(scope="module")
def head_findings():
    """ONE full-analysis run over HEAD shared by the read-only tests
    (a run is ~3.5 s; tier-1's budget prefers one to a dozen)."""
    findings, _ = paddlelint.run_passes()
    return findings


def _ctx_from_source(src, rel="m.py"):
    """ProjectContext over one synthetic file."""
    d = tempfile.mkdtemp(prefix="lint_test_")
    path = os.path.join(d, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(src))
    return core.ProjectContext(d, [rel]), d


FIXTURE_DIRS = {
    "lock-order": "lock_order",
    "blocking-under-lock": "blocking_under_lock",
    "unlocked-shared-state": "unlocked_shared_state",
    "use-after-donate": "use_after_donate",
    "hot-sync": "hot_sync",
}

# the rule each corpus MUST trip (red is necessary; red on the RIGHT
# rule is the proof the pass still understands its bug class)
FIXTURE_EXPECT = {
    "lock-order": {"lock-cycle", "lock-self-cycle"},
    "blocking-under-lock": {"file-io-under-lock", "wait-under-lock",
                            "unbounded-acquire"},
    "unlocked-shared-state": {"unlocked-shared-write"},
    "use-after-donate": {"use-after-donate"},
    "hot-sync": {"sync-in-hot-region"},
}


# ---------------------------------------------------------------- HEAD

def test_paddlelint_green_on_head(head_findings):
    """The acceptance gate: zero unsuppressed findings at HEAD, every
    suppression carrying a reason, exit code 0."""
    unsup = [f for f in head_findings if not f.suppressed]
    assert unsup == [], "\n".join(f.render() for f in unsup)
    for f in head_findings:
        assert f.reason and f.reason.strip(), f.render()
    assert paddlelint.main([]) == 0


def test_each_pass_green_on_head_individually(head_findings):
    """Per-pass green, from the shared run (the passes are
    independent: a full-run finding carries its pass name); hot-sync
    additionally proves a standalone --select run below."""
    for name in PASS_NAMES:
        bad = [f for f in head_findings
               if f.pass_name == name and not f.suppressed]
        assert bad == [], f"{name}: " + "\n".join(
            f.render() for f in bad)


# ------------------------------------------------------------ fixtures

@pytest.mark.parametrize("name", sorted(FIXTURE_DIRS))
def test_pass_red_on_fixture_corpus(name):
    root = os.path.join(FIXTURES, FIXTURE_DIRS[name])
    findings, _ = paddlelint.run_passes(root=root, select=[name])
    live = [f for f in findings
            if not f.suppressed and f.pass_name == name]
    assert live, f"{name} corpus produced no findings"
    rules = {f.rule for f in live}
    missing = FIXTURE_EXPECT[name] - rules
    assert not missing, \
        f"{name} corpus missed expected rule(s) {missing}; got {rules}"
    # every finding names file:line and the violated rule
    for f in live:
        assert f.file and f.line >= 0 and f.rule, f.render()
    # and the CLI exits 1 on the corpus
    rc = paddlelint.main([root, "--select", name])
    assert rc == 1


def test_symlinked_repo_root_gets_curated_fileset(tmp_path):
    """Any repo-SHAPED root — a symlinked spelling, a worktree, a CI
    copy — must resolve to the curated fileset (fixtures excluded),
    not corpus mode: else a second checkout lints the known-bad
    corpora as real findings."""
    link = str(tmp_path / "repolink")
    os.symlink(REPO, link)
    findings, ctx = paddlelint.run_passes(root=link)
    assert not any("fixtures" in sf.rel for sf in ctx.files)
    assert [f for f in findings if not f.suppressed] == []
    # a partial copy with the repo layout: curated mode, no fixtures
    copy = tmp_path / "checkout"
    for rel in ("paddle_tpu/__init__.py", "tools/lint/__init__.py",
                "tools/lint/fixtures/lock_order/deadlock.py"):
        dst = copy / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO, rel), dst)
    _, ctx2 = paddlelint.run_passes(root=str(copy))
    assert not any("fixtures" in sf.rel for sf in ctx2.files)


def test_fixtures_excluded_from_default_fileset():
    rels = core.default_fileset(REPO)
    assert not any("fixtures" in r for r in rels)
    assert "bench.py" in rels
    assert "paddle_tpu/inference/serving.py" in rels
    assert "tools/paddlelint.py" in rels


# ------------------------------------------------- targeted bug shapes

def test_lock_order_cycle_and_reentrant_exemption():
    ctx, d = _ctx_from_source("""
        import threading
        _a = threading.Lock()
        _b = threading.Lock()
        _r = threading.RLock()

        def one():
            with _a:
                with _b:
                    pass

        def two():
            with _b:
                with _a:
                    pass

        def reentrant_ok():
            with _r:
                with _r:
                    pass
        """)
    try:
        from lint.lock_order import LockOrderPass
        fs = LockOrderPass().run(ctx)
        assert any(f.rule == "lock-cycle" for f in fs)
        # the RLock self-nest is exempt by construction
        assert not any(f.rule == "lock-self-cycle" for f in fs)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_blocking_under_lock_via_call_chain():
    """The PR 10 trace.finish() shape: the blocking op is one call hop
    away from the lock."""
    ctx, d = _ctx_from_source("""
        import threading
        _lock = threading.Lock()

        def _emit(path):
            with open(path, "a") as f:
                f.write("x")

        def close(path):
            with _lock:
                _emit(path)
        """)
    try:
        from lint.blocking_under_lock import BlockingUnderLockPass
        fs = BlockingUnderLockPass().run(ctx)
        hits = [f for f in fs if f.rule == "file-io-under-lock"]
        assert any("via _emit" in f.message for f in hits), \
            [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_blocking_event_wait_under_lock_flagged():
    """Event.wait blocks while HOLDING enclosing locks (unlike
    Condition.wait, which releases its own) — under a lock it is the
    hang class the pass exists to catch."""
    ctx, d = _ctx_from_source("""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._done_event = threading.Event()
                self._cv = threading.Condition()

            def bad(self):
                with self._lock:
                    self._done_event.wait()

            def fine(self):
                with self._cv:
                    self._cv.wait(timeout=1.0)
        """)
    try:
        from lint.blocking_under_lock import BlockingUnderLockPass
        fs = [f for f in BlockingUnderLockPass().run(ctx)
              if f.rule == "wait-under-lock"]
        assert len(fs) == 1 and "_done_event" in fs[0].message, \
            [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_blocking_allowed_inner_lock_does_not_mask_outer():
    """An ALLOWED inner lock must not suppress blocking work that ALSO
    runs under a disallowed outer lock (the PR 10 class, nested)."""
    ctx, d = _ctx_from_source("""
        import threading

        class monitorlike:
            pass

        class Engine:
            def __init__(self):
                self._cv = threading.Condition()

            def close(self, path):
                with self._cv:
                    with _export_lock:
                        with open(path, "a") as f:
                            f.write("x")

        _export_lock = threading.Lock()
        """, rel="paddle_tpu/profiler/monitor.py")
    try:
        from lint.blocking_under_lock import BlockingUnderLockPass
        fs = [f for f in BlockingUnderLockPass().run(ctx)
              if f.rule == "file-io-under-lock"]
        # the file's _export_lock IS the allowed identity, but the
        # engine's condition lock is held too -> unsuppressed
        assert fs and not any(f.suppressed for f in fs), \
            [f.render() for f in fs]
        assert any("_cv" in f.message for f in fs)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_blocking_pass_str_join_not_flagged():
    ctx, d = _ctx_from_source("""
        import threading
        _lock = threading.Lock()

        def render(parts, sep):
            with _lock:
                a = ", ".join(parts)
                b = sep.join(parts)
                import os
                c = os.path.join("a", "b")
            return a, b, c
        """)
    try:
        from lint.blocking_under_lock import BlockingUnderLockPass
        fs = [f for f in BlockingUnderLockPass().run(ctx)
              if not f.suppressed]
        assert fs == [], [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_unlocked_shared_state_lock_discipline_is_green():
    """The same engine shape with the lock held on both sides: green —
    the pass flags missing locks, not threads."""
    ctx, d = _ctx_from_source("""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats = {}
                self._thread = threading.Thread(target=self._loop)
                self._thread.start()

            def _loop(self):
                with self._lock:
                    self._stats["n"] = self._stats.get("n", 0) + 1

            def report(self):
                with self._lock:
                    return dict(self._stats)
        """)
    try:
        from lint.unlocked_shared_state import UnlockedSharedStatePass
        fs = UnlockedSharedStatePass().run(ctx)
        assert fs == [], [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_unlocked_shared_state_stop_flag_exempt():
    ctx, d = _ctx_from_source("""
        import threading

        class Engine:
            def __init__(self):
                self._stop = False
                self._thread = threading.Thread(target=self._loop)
                self._thread.start()

            def _loop(self):
                while not self._stop:
                    pass

            def shutdown(self):
                self._stop = True
        """)
    try:
        from lint.unlocked_shared_state import UnlockedSharedStatePass
        fs = UnlockedSharedStatePass().run(ctx)
        assert fs == [], [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_use_after_donate_multiline_call_args_not_flagged():
    """A donating call wrapped across lines reads its own arguments
    BEFORE the donation takes effect — reformatting the correct idiom
    must not go red (the taint anchors at the call's END line)."""
    ctx, d = _ctx_from_source("""
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def update(pool, x):
            return pool + x

        def wrapped(pool, x):
            out = update(
                pool,
                x)
            return out

        def still_bad(pool, x):
            out = update(
                pool,
                x)
            return out + pool
        """)
    try:
        from lint.use_after_donate import UseAfterDonatePass
        fs = UseAfterDonatePass().run(ctx)
        assert len(fs) == 1, [f.render() for f in fs]
        assert fs[0].line > 0 and "still_bad" not in fs[0].message
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_use_after_donate_rebind_is_clean():
    ctx, d = _ctx_from_source("""
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def update(pool, x):
            return pool + x

        def good(pool, x):
            pool = update(pool, x)
            return pool * 2

        def bad(pool, x):
            out = update(pool, x)
            return out + pool
        """)
    try:
        from lint.use_after_donate import UseAfterDonatePass
        fs = UseAfterDonatePass().run(ctx)
        assert len(fs) == 1 and fs[0].rule == "use-after-donate"
        assert "pool" in fs[0].message
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_use_after_donate_annotated_rebind_is_clean():
    """`pool: Pool = step(pool, x)` is the same correct idiom as the
    unannotated spelling — ast.AnnAssign must clear the taint (and an
    annotated jit binding must register as a donating callable)."""
    ctx, d = _ctx_from_source("""
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def update(pool, x):
            return pool + x

        step = jax.jit(lambda p, x: p + x, donate_argnums=(0,))

        def good_annotated(pool, x):
            pool: object = update(pool, x)
            return pool * 2

        def annotated_binding(pool, x):
            fn: object = jax.jit(lambda p, y: p, donate_argnums=(0,))
            fn(pool, x)
            return pool.sum()

        def bad(pool, x):
            out = update(pool, x)
            return out + pool
        """)
    try:
        from lint.use_after_donate import UseAfterDonatePass
        fs = UseAfterDonatePass().run(ctx)
        msgs = [f.render() for f in fs]
        assert len(fs) == 2, msgs
        assert not any("good_annotated" in m for m in msgs)
        # the annotated local jit binding still registers: its
        # un-rebound use IS a finding
        assert any("fn()" in f.message for f in fs), msgs
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_unlocked_shared_state_annotated_write_flagged():
    """`self._count: int = ...` in a thread context is the same
    unlocked write as the unannotated spelling — ast.AnnAssign must
    not be invisible to the pass."""
    ctx, d = _ctx_from_source("""
        import threading

        class Eng:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                threading.Thread(target=self._loop).start()

            def _loop(self):
                while True:
                    self._count: int = self._count + 1

            def report(self):
                return self._count
        """)
    try:
        from lint.unlocked_shared_state import UnlockedSharedStatePass
        fs = UnlockedSharedStatePass().run(ctx)
        assert any(f.rule == "unlocked-shared-write" and
                   "_count" in f.message for f in fs), \
            [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_unlocked_shared_state_reports_every_write_site():
    """One finding PER distinct unprotected write site: a line-scoped
    suppression on one site must not grant the whole attribute
    immunity — the second, unjustified mutation still goes red."""
    ctx, d = _ctx_from_source("""
        import threading

        class Eng:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats = {}
                threading.Thread(target=self._loop).start()
                threading.Thread(target=self._gc).start()

            def _loop(self):
                self._stats["n"] = 1  # lint-ok[unlocked-shared-state]: justified here

            def _gc(self):
                self._stats.clear()

            def report(self):
                return dict(self._stats)
        """)
    try:
        from lint.unlocked_shared_state import UnlockedSharedStatePass
        from lint.core import apply_suppressions
        fs = apply_suppressions(ctx, UnlockedSharedStatePass().run(ctx))
        stats = [f for f in fs if "_stats" in f.message]
        assert len(stats) == 2, [f.render() for f in fs]
        unsup = [f for f in stats if not f.suppressed]
        assert len(unsup) == 1 and "_gc" in unsup[0].message, \
            [f.render() for f in stats]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_unlocked_shared_state_thread_entry_never_locked_context():
    """A lock-held intra-file call site of a thread-entry method must
    NOT exempt it: the Thread start is a lock-free call site the scan
    cannot see."""
    ctx, d = _ctx_from_source("""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats = {}
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def _run(self):
                self._stats["n"] = 1

            def kick(self):
                with self._lock:
                    self._run()

            def report(self):
                return dict(self._stats)
        """)
    try:
        from lint.unlocked_shared_state import UnlockedSharedStatePass
        fs = UnlockedSharedStatePass().run(ctx)
        assert any(f.rule == "unlocked-shared-write" for f in fs), \
            [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_use_after_donate_exclusive_branches_not_flagged():
    """A donate in one arm of an if cannot reach a read in the other
    arm; sibling ifs (both can run) still propagate."""
    ctx, d = _ctx_from_source("""
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def update(pool, x):
            return pool + x

        def exclusive_ok(pool, x, cond):
            if cond:
                return update(pool, x)
            else:
                return pool * 2

        def sibling_bad(pool, x, cond):
            if cond:
                out = update(pool, x)
            if x is not None:
                return pool + 1
            return out
        """)
    try:
        from lint.use_after_donate import UseAfterDonatePass
        fs = UseAfterDonatePass().run(ctx)
        assert len(fs) == 1, [f.render() for f in fs]
        assert fs[0].line > 0
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_unbounded_acquire_blocking_true_flagged():
    """acquire(True) / acquire(blocking=True) ARE the unbounded form;
    timeout=, blocking=False and (blocking, timeout) are bounded."""
    ctx, d = _ctx_from_source("""
        import threading
        _l = threading.Lock()

        def a():
            _l.acquire(blocking=True)   # unbounded, spelled out

        def b():
            _l.acquire(True)            # unbounded, spelled out

        def c():
            _l.acquire(timeout=1.0)     # bounded

        def e():
            _l.acquire(blocking=False)  # non-blocking probe

        def f():
            _l.acquire(True, 5)         # bounded (timeout slot)

        def g():
            _l.acquire(1)               # truthy int: unbounded too

        def h():
            _l.acquire(blocking=True, timeout=2.0)  # bounded: timeout
            _l.acquire(timeout=-1)      # -1 = wait forever: unbounded
            _l.acquire(True, -1.0)      # same, positional slot
        """)
    try:
        from lint.blocking_under_lock import BlockingUnderLockPass
        fs = [f for f in BlockingUnderLockPass().run(ctx)
              if f.rule == "unbounded-acquire"]
        assert sorted(f.line for f in fs) == [6, 9, 21, 25, 26], \
            [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_lock_param_does_not_resolve_to_class_field():
    """A parameter that merely shares a class lock field's name must
    not resolve to it — else clean code reports a fake self-cycle."""
    ctx, d = _ctx_from_source("""
        import threading

        class Engine:
            def __init__(self):
                self.lock = threading.Lock()

            def helper(self, lock):
                with lock:
                    with self.lock:
                        return 1
        """)
    try:
        from lint.lock_order import LockOrderPass
        fs = LockOrderPass().run(ctx)
        assert fs == [], [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_scoped_lint_ok_hot_sync_honored_by_both_gates():
    """`# lint-ok[hot-sync]: <why>` must silence the pass AND the
    legacy check_source — the two tier-1 gates may never disagree on
    a line. An unscoped lint-ok silences neither."""
    from lint.hot_sync import check_source
    marked = "\n".join([
        "class TrainStep:",
        "    def __call__(self, *batch):",
        "        loss = self._jitted(*batch)",
        "        return loss.item()  # lint-ok[hot-sync]: test reason",
    ])
    assert check_source(marked, ["TrainStep.__call__"], "x.py") == []
    unscoped = marked.replace("lint-ok[hot-sync]: test reason",
                              "lint-ok: generic")
    assert check_source(unscoped, ["TrainStep.__call__"], "x.py")
    # and the framework side: the unscoped marker does not suppress
    # a hot-sync finding
    ctx, d = _ctx_from_source(unscoped,
                              rel="paddle_tpu/jit/api.py")
    try:
        from lint.hot_sync import HotSyncPass
        fs = core.apply_suppressions(ctx, HotSyncPass().run(ctx))
        assert any(f.rule == "sync-in-hot-region" and not f.suppressed
                   for f in fs)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_unlocked_shared_state_disjoint_locks_still_race():
    """Writer under lock A, reader under lock B: the same race as no
    lock at all — identity matters, not the mere presence of a lock."""
    ctx, d = _ctx_from_source("""
        import threading

        class Engine:
            def __init__(self):
                self._stats_lock = threading.Lock()
                self._export_lock = threading.Lock()
                self._stats = {}
                self._thread = threading.Thread(target=self._loop)
                self._thread.start()

            def _loop(self):
                with self._stats_lock:
                    self._stats["n"] = 1

            def report(self):
                with self._export_lock:
                    return dict(self._stats)
        """)
    try:
        from lint.unlocked_shared_state import UnlockedSharedStatePass
        fs = UnlockedSharedStatePass().run(ctx)
        assert any(f.rule == "unlocked-shared-write" and
                   "DIFFERENT locks" in f.message for f in fs), \
            [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


# --------------------------------------------------------- suppression

def test_suppression_scoped_marker_suppresses_and_reaches_ledger():
    ctx, d = _ctx_from_source("""
        import threading
        _lock = threading.Lock()

        def export(path):
            with _lock:
                with open(path, "a") as f:  # lint-ok[blocking-under-lock]: bounded 1-line append, callers tolerate the stall
                    f.write("x")
        """)
    try:
        from lint.blocking_under_lock import BlockingUnderLockPass
        fs = core.apply_suppressions(ctx, BlockingUnderLockPass().run(ctx))
        hits = [f for f in fs if f.rule == "file-io-under-lock"]
        assert hits and all(f.suppressed for f in hits)
        assert "bounded 1-line append" in hits[0].reason
        rec = hits[0].record()
        assert rec["kind"] == "lint" and rec["suppressed"] is True
        assert rec["reason"]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_suppression_wrong_scope_does_not_suppress():
    ctx, d = _ctx_from_source("""
        import threading
        _lock = threading.Lock()

        def export(path):
            with _lock:
                with open(path, "a") as f:  # lint-ok[hot-sync]: wrong pass scope
                    f.write("x")
        """)
    try:
        from lint.blocking_under_lock import BlockingUnderLockPass
        fs = core.apply_suppressions(ctx, BlockingUnderLockPass().run(ctx))
        hits = [f for f in fs if f.rule == "file-io-under-lock"]
        assert hits and not any(f.suppressed for f in hits)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_suppression_requires_reason():
    """A reasonless lint-ok (or hot-sync-ok) marker is itself a
    finding — never an exemption."""
    ctx, d = _ctx_from_source("""
        import threading
        _lock = threading.Lock()

        def export(path):
            with _lock:
                with open(path, "a") as f:  # lint-ok:
                    f.write("x")
        """)
    try:
        from lint.blocking_under_lock import BlockingUnderLockPass
        fs = core.apply_suppressions(ctx, BlockingUnderLockPass().run(ctx))
        assert any(f.rule == "file-io-under-lock" and not f.suppressed
                   for f in fs)
        assert any(f.rule == "suppression-needs-reason" for f in fs)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_reasonless_hot_sync_ok_marker_is_flagged():
    ctx, d = _ctx_from_source("""
        def f(x):
            return x  # hot-sync-ok:
        """)
    try:
        fs = core.apply_suppressions(ctx, [])
        assert any(f.rule == "suppression-needs-reason" for f in fs)
    finally:
        shutil.rmtree(d, ignore_errors=True)


# ------------------------------------------------------------- ratchet

def test_baseline_ratchet_refuses_to_loosen(tmp_path):
    from lint.core import (check_baseline, load_baseline,
                           update_baseline)
    path = str(tmp_path / "LINT_BASELINE.json")
    with open(path, "w") as f:
        json.dump({"schema": core.BASELINE_SCHEMA,
                   "passes": {"hot-sync": {"suppressed": 2}}}, f)
    bl = load_baseline(path)
    # growth fails
    errs = check_baseline(bl, {"hot-sync": 3}, ["hot-sync"])
    assert errs and "exceeds the baseline" in errs[0]
    # --update refuses to raise and leaves the file untouched
    wrote, refused = update_baseline(path, load_baseline(path),
                                     {"hot-sync": 3}, ["hot-sync"])
    assert refused == ["hot-sync"] and not wrote
    assert load_baseline(path)["passes"]["hot-sync"]["suppressed"] == 2
    # shrink ratchets down
    wrote, refused = update_baseline(path, load_baseline(path),
                                     {"hot-sync": 1}, ["hot-sync"])
    assert wrote and not refused
    assert load_baseline(path)["passes"]["hot-sync"]["suppressed"] == 1
    # equal count is clean
    assert check_baseline(load_baseline(path), {"hot-sync": 1},
                          ["hot-sync"]) == []
    # --update never CREATES a missing entry (hand edit, in the diff)
    wrote, refused = update_baseline(path, load_baseline(path),
                                     {"lock-order": 0}, ["lock-order"])
    assert refused == ["lock-order"] and not wrote
    assert "lock-order" not in load_baseline(path)["passes"]


def test_corrupt_baseline_fails_closed(tmp_path):
    """A PRESENT but unreadable baseline must exit 1, not silently
    disable the ratchet."""
    root = tmp_path / "mini"
    (root / "paddle_tpu").mkdir(parents=True)
    (root / "tools" / "lint").mkdir(parents=True)
    (root / "paddle_tpu" / "__init__.py").write_text("x = 1\n")
    (root / "LINT_BASELINE.json").write_text("{broken")
    assert paddlelint.main([str(root)]) == 1


def test_unparseable_hot_file_gets_its_own_rule():
    """A syntax error in a fenced file is a parse failure, not a
    renamed region — the ledger must not send triage to HOT_REGIONS."""
    d = tempfile.mkdtemp(prefix="lint_test_")
    try:
        rel = "paddle_tpu/inference/serving.py"  # a fenced path
        path = os.path.join(d, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write("def broken(:\n")
        ctx = core.ProjectContext(d, [rel])
        from lint.hot_sync import HotSyncPass
        fs = [f for f in HotSyncPass().run(ctx) if f.file == rel]
        assert any(f.rule == "hot-file-unparseable" for f in fs), \
            [f.render() for f in fs]
        assert not any(f.rule == "hot-region-missing" for f in fs), \
            [f.render() for f in fs]
    finally:
        shutil.rmtree(d, ignore_errors=True)


def test_missing_explicit_baseline_fails_closed(tmp_path):
    """An explicitly requested --baseline that does not exist must
    exit 1 — a typo'd CI flag must not silently disable the ratchet.
    A missing DEFAULT baseline (fixture-corpus roots) stays fine."""
    root = tmp_path / "mini"
    (root / "paddle_tpu").mkdir(parents=True)
    (root / "tools" / "lint").mkdir(parents=True)
    (root / "paddle_tpu" / "__init__.py").write_text("x = 1\n")
    missing = str(tmp_path / "no_such_baseline.json")
    assert paddlelint.main([str(root), "--baseline", missing]) == 1
    # no baseline anywhere, none requested: clean run, no ratchet
    # (hot-sync excluded: the mini root legitimately lacks hot files)
    assert paddlelint.main([str(root), "--select", "lock-order"]) == 0


def test_repo_baseline_matches_head_counts(head_findings):
    """LINT_BASELINE.json is in sync: every pass entry present and the
    gate (main with the real baseline) green."""
    bl = core.load_baseline(os.path.join(REPO, "LINT_BASELINE.json"))
    assert bl is not None and bl.get("schema") == core.BASELINE_SCHEMA
    for name in PASS_NAMES:
        assert name in bl["passes"], name
    counts = core.suppressed_counts(head_findings)
    for name in PASS_NAMES:
        assert counts.get(name, 0) <= \
            bl["passes"][name]["suppressed"], name


def test_cli_ratchet_failure_exit_code(tmp_path):
    """A baseline tighter than reality fails the CLI with exit 1."""
    bl_path = str(tmp_path / "bl.json")
    with open(bl_path, "w") as f:
        json.dump({"schema": core.BASELINE_SCHEMA,
                   "passes": {name: {"suppressed": 0}
                              for name in PASS_NAMES}}, f)
    # hot-sync has real suppressions at HEAD -> ratchet error
    rc = paddlelint.main([REPO, "--baseline", bl_path])
    assert rc == 1


# ------------------------------------------------------- hot-sync shim

def test_shim_cli_behavior_unchanged():
    tool = _load_tool("check_no_hot_sync")
    # the legacy public surface survives
    for attr in ("HOT_REGIONS", "PATTERNS", "ALLOW_MARKER",
                 "check_source", "check_repo", "main"):
        assert hasattr(tool, attr), attr
    assert tool.main([REPO]) == 0
    # identical verdict strings on a planted violation
    src = "\n".join([
        "class TrainStep:",
        "    def __call__(self, *batch):",
        "        loss = self._jitted(*batch)",
        "        return " + "float(loss.item())",
    ])
    errs = tool.check_source(src, ["TrainStep.__call__"], "x.py")
    assert len(errs) == 2
    assert all(e.startswith("x.py:4: ") for e in errs)
    # region-gone is a violation naming the legacy table location
    assert tool.check_source(src, ["TrainStep.gone"], "x.py")


def test_shim_subprocess_stdout_and_exit():
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_no_hot_sync.py"),
         REPO], capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert out.stdout.strip() == \
        f"OK: {len(_load_tool('check_no_hot_sync').HOT_REGIONS)} " \
        "hot file(s) clean"


def test_shim_and_pass_agree_on_repo():
    tool = _load_tool("check_no_hot_sync")
    assert tool.check_repo(REPO) == []
    findings, _ = paddlelint.run_passes(select=["hot-sync"])
    assert [f for f in findings if not f.suppressed] == []


# ------------------------------------------------------- lint schema

def test_lint_schema_valid_and_violations():
    cms = _load_tool("check_metrics_schema")
    base = {"ts": 1.0, "rank": 0, "kind": "lint",
            "pass": "lock-order", "rule": "lock-cycle",
            "file": "paddle_tpu/x.py", "line": 12,
            "severity": "error", "message": "cycle a->b->a",
            "suppressed": False}
    assert cms.validate_line(json.dumps(base)) == []
    sup = dict(base, suppressed=True, reason="proven single-threaded")
    assert cms.validate_line(json.dumps(sup)) == []
    # suppressed without reason
    bad = dict(base, suppressed=True)
    assert cms.validate_line(json.dumps(bad))
    bad = dict(base, suppressed=True, reason="  ")
    assert cms.validate_line(json.dumps(bad))
    # unknown pass name
    bad = dict(base)
    bad["pass"] = "made-up"
    assert cms.validate_line(json.dumps(bad))
    # bad severity / negative line / empty file / missing keys
    assert cms.validate_line(json.dumps(dict(base, severity="meh")))
    assert cms.validate_line(json.dumps(dict(base, line=-1)))
    assert cms.validate_line(json.dumps(dict(base, file="")))
    gone = dict(base)
    del gone["rule"]
    assert cms.validate_line(json.dumps(gone))


def test_schema_pass_set_matches_framework():
    cms = _load_tool("check_metrics_schema")
    assert cms.LINT_PASSES == set(KNOWN_PASS_NAMES)


def test_findings_jsonl_roundtrip_validates(tmp_path, head_findings):
    cms = _load_tool("check_metrics_schema")
    out = str(tmp_path / "lint.jsonl")
    assert head_findings, "HEAD carries suppressed findings (hot-sync)"
    paddlelint.write_jsonl(out, head_findings)
    assert cms.validate_file(out) == []


# ---------------------------------------------------------- obs_report

def test_obs_report_renders_lint_section(tmp_path):
    obs = _load_tool("obs_report")
    recs = [
        {"ts": 1.0, "rank": 0, "kind": "lint", "pass": "hot-sync",
         "rule": "sync-in-hot-region", "file": "a.py", "line": 3,
         "severity": "error", "message": "device_get in decode loop",
         "suppressed": True, "reason": "the one deliberate sync"},
        {"ts": 1.0, "rank": 0, "kind": "lint", "pass": "lock-order",
         "rule": "lock-cycle", "file": "b.py", "line": 9,
         "severity": "error", "message": "cycle a->b->a",
         "suppressed": False},
    ]
    text = obs.render(recs)
    assert "== lint ==" in text
    assert "1 finding(s), 1 suppressed" in text
    assert "lock-order/lock-cycle" in text and "b.py:9" in text
    assert "hot-sync=1" in text
    # no lint records -> no section
    assert "== lint ==" not in obs.render(
        [{"ts": 1.0, "rank": 0, "kind": "event", "event": "x"}])


# ------------------------------------------------------------- driver

def test_driver_list_and_unknown_pass():
    assert paddlelint.main(["--list"]) == 0
    assert paddlelint.main([REPO, "--select", "nope"]) == 2


def test_driver_writes_env_metrics_file(tmp_path, monkeypatch):
    cms = _load_tool("check_metrics_schema")
    out = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", out)
    assert paddlelint.main([REPO, "--select", "hot-sync"]) == 0
    assert os.path.exists(out)
    recs = [json.loads(x) for x in open(out) if x.strip()]
    assert recs and all(r["kind"] == "lint" for r in recs)
    assert cms.validate_file(out) == []
