"""paddle.dataset.uci_housing — legacy reader-creator API over
paddle_tpu.text.UCIHousing.

Parity: /root/reference/python/paddle/dataset/uci_housing.py.
"""
import numpy as np

from ..text import UCIHousing

__all__ = []

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
                 "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT"]


def _reader_creator(mode):
    def reader():
        ds = UCIHousing(mode=mode)
        for i in range(len(ds)):
            x, y = ds[i]
            yield np.asarray(x, np.float32), np.asarray(y, np.float32)

    return reader


def train():
    return _reader_creator("train")


def test():
    return _reader_creator("test")


def predict_reader():
    """First 100 test samples, features only (inference feed)."""
    def reader():
        for i, (x, _) in enumerate(_reader_creator("test")()):
            if i == 100:
                break
            yield (x,)

    return reader


def fetch():
    from .common import download
    download("http://paddlemodels.bj.bcebos.com/uci_housing/housing.data",
             "uci_housing", None)
