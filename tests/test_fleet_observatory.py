"""The fleet observatory: cross-engine request journeys, router fleet
snapshots, and edge-triggered pressure events
(profiler/fleet_observatory.py — docs/OBSERVABILITY.md "The fleet
observatory").

- the journey join, end to end: ONE schema-valid `kind:"journey"`
  record per handed-off request, its `request_id` matching the route
  record AND both engine-side `kind:"request"` records, the four
  phases telescoping into the latency, the handoff gap MEASURED
  (export→adopt stamps), TTFT attributed to the prefill engine
- `kind:"journey"` / `kind:"fleet"` schema tables: good synthetic
  records pass, each broken invariant is flagged by name
- FleetPressure discipline: every detector edge-triggered (one event
  per episode, re-armed on clear), the gap spike never folded into
  its own baseline
- the wedged-engine drill: one engine's scheduler lock held from
  outside — `router.load_report()` still rolls up (the stuck engine
  degrades to `unavailable`), the fleet snapshot still emits, and
  `submit` places on the healthy mate
"""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
from paddle_tpu.inference import GenerationEngine, ServingRouter
from paddle_tpu.profiler import fleet_observatory as fobs
from paddle_tpu.profiler import flight_recorder, monitor

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick gate no

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import check_metrics_schema as cms  # noqa: E402


def _tiny_lm(seed=0):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


MODEL = _tiny_lm()


def _kind(lines, kind):
    return [r for r in lines if r.get("kind") == kind]


def _validate(rec):
    return cms.validate_line(json.dumps(rec))


# -- the journey join, end to end ----------------------------------------

class TestJourneyEndToEnd:
    def test_one_journey_per_handoff_joins_the_pair(self, tmp_path,
                                                    monkeypatch):
        mfile = tmp_path / "metrics.jsonl"
        monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
        fobs.reset()
        router = ServingRouter.disaggregated(
            MODEL, n_pages=64, page_size=4, max_batch=2,
            max_new_tokens=8, name="fo_live")
        try:
            h = router.submit(np.arange(1, 7), max_new_tokens=3,
                              deadline_ms=120_000)
            out = h.result(300)
            assert h.request_id  # stamped at router.submit
            router._fleet_mon.snapshot()  # cadence won't fire in-test
        finally:
            router.shutdown()
        lines = [json.loads(l) for l in
                 mfile.read_text().splitlines() if l.strip()]

        journeys = _kind(lines, "journey")
        assert len(journeys) == 1  # ONE record per handed-off request
        j = journeys[0]
        assert _validate(j) == []
        assert j["request_id"] == h.request_id
        assert j["router"] == "fo_live"
        assert j["prefill_engine"] == "fo_live_prefill"
        assert j["decode_engine"] == "fo_live_decode"
        assert j["outcome"] == "completed"
        assert j["slo_class"] == "standard"  # 120s deadline
        assert j["prompt_tokens"] == 6
        assert j["generated_tokens"] == len(out)
        # the chain carried the prefill's whole context; pages reconcile
        assert j["pages_moved"] == -(-j["chain_tokens"]
                                     // j["page_size"])
        # four MEASURED phases telescope into the journey latency
        phases = (j["queue_s"] + j["prefill_s"] + j["handoff_gap_s"]
                  + j["decode_s"])
        assert abs(phases - j["latency_s"]) < 1e-3
        assert j["handoff_gap_s"] >= 0.0
        assert 0.0 <= j["ttft_s"] <= j["latency_s"]
        assert j["deadline_met"] is True

        # the join: the SAME id on the route record and BOTH halves
        dispatched = [r for r in _kind(lines, "route")
                      if r["outcome"] == "dispatched"]
        assert [r.get("request_id") for r in dispatched] \
            == [h.request_id]
        reqs = [r for r in _kind(lines, "request")
                if r["request_id"] == h.request_id]
        by_outcome = {r["outcome"]: r for r in reqs}
        assert set(by_outcome) == {"handoff", "completed"}
        pre, dec = by_outcome["handoff"], by_outcome["completed"]
        assert pre["engine"] == "fo_live_prefill"
        assert dec["engine"] == "fo_live_decode"
        # cross-stamped: each half names the other
        assert pre["handoff_of"] == "fo_live_decode"
        assert dec["handoff_of"] == "fo_live_prefill"
        # decode re-counts the prefill's streamed first token
        assert pre["generated_tokens"] == 1
        assert dec["generated_tokens"] == j["generated_tokens"]

        # fleet snapshots rode the same file (the forced one above)
        fleets = _kind(lines, "fleet")
        assert fleets and all(_validate(r) == [] for r in fleets)
        assert {r["router"] for r in fleets} == {"fo_live"}

        # obs_report joins the pair from the records
        import obs_report
        text = obs_report.render(lines)
        assert "== journeys ==" in text
        assert "pair reconciliation: 1/1" in text
        assert "MISMATCH" not in text

    def test_journey_ring_and_debug_bundle(self, tmp_path):
        # the run above is not required: any journey in the ring works,
        # so emit one synthetically through the module surfaces
        fobs.reset()
        assert fobs.journeys_tail() == []
        state = fobs.fleet_state()
        assert "routers" in state and "journeys_tail" in state
        # the bundle hook is registered on first FleetMonitor; a dump
        # must carry fleet_state.json
        eng = GenerationEngine(MODEL, n_pages=16, page_size=4,
                               max_batch=1, max_new_tokens=4,
                               name="fo_bundle_eng")
        try:
            router = ServingRouter([eng], name="fo_bundle",
                                   fleet_snapshot_s=1000.0)
            assert router._fleet_mon.snapshot() is not None
            bundle = flight_recorder.dump("fleet-test",
                                          base_dir=str(tmp_path))
            path = os.path.join(bundle, "fleet_state.json")
            assert os.path.exists(path)
            payload = json.loads(open(path).read())
            assert "fo_bundle" in payload["routers"]
            last = payload["routers"]["fo_bundle"]["last_snapshot"]
            assert last["kind"] == "fleet"
        finally:
            eng.shutdown()

    def test_snapshot_cadence_claims_one_window(self):
        eng = GenerationEngine(MODEL, n_pages=16, page_size=4,
                               max_batch=1, max_new_tokens=4,
                               name="fo_cad_eng")
        try:
            router = ServingRouter([eng], name="fo_cad")
            mon = fobs.FleetMonitor(router, interval_s=1000.0)
            # cadence counts from construction: nothing is due yet
            assert mon.maybe_snapshot() is None
            # a forced snapshot ignores the cadence
            forced = mon.snapshot()
            assert forced is not None and forced["kind"] == "fleet"
            assert _validate(forced) == []
            # forcing does not open the window either
            assert mon.maybe_snapshot() is None
            # an elapsed interval does: backdate the claim stamp
            mon._t_last -= 2000.0
            due = mon.maybe_snapshot()
            assert due is not None and _validate(due) == []
            assert mon.maybe_snapshot() is None  # window claimed
        finally:
            eng.shutdown()

    def test_maybe_snapshot_rate_window_spans_the_interval(self):
        """maybe_snapshot claims the cadence window (overwriting
        _t_last) BEFORE the snapshot runs — the rate window must still
        reach back to the PREVIOUS snapshot, not the milliseconds the
        claim-to-report gap took, or every rate inflates by the
        interval/milliseconds ratio (~1000x at the 5 s default)."""
        eng = GenerationEngine(MODEL, n_pages=16, page_size=4,
                               max_batch=1, max_new_tokens=4,
                               name="fo_win_eng")
        try:
            router = ServingRouter([eng], name="fo_win")
            mon = fobs.FleetMonitor(router, interval_s=1000.0)
            assert mon.snapshot() is not None  # anchors the window
            t0 = time.perf_counter()
            with router._lock:  # three arrivals inside the window
                router._stats["requests"] += 3
            time.sleep(0.25)
            mon._t_last -= 2000.0  # cadence due: the production path
            rec = mon.maybe_snapshot()
            elapsed = time.perf_counter() - t0
            assert rec is not None
            assert 0.25 <= rec["window_s"] <= elapsed + 0.05
            # the rate is delta / THAT window — ~12/s here, not the
            # ~1000x-inflated delta / load_report-milliseconds figure
            assert rec["arrival_rate"] == pytest.approx(
                3 / rec["window_s"], rel=0.01)
            assert rec["arrival_rate"] < 100.0
        finally:
            eng.shutdown()


# -- the snapshot-interval env knob --------------------------------------

class _RouterStub:
    """weakref-able stand-in: interval parsing never touches the
    router beyond its name/engines."""
    name = "fo_env"
    engines = ()


class TestSnapshotIntervalEnv:
    def test_rejects_non_finite_and_junk(self, monkeypatch):
        # json.loads parses NaN/Infinity tokens, and `now - t < nan`
        # is always False — an accepted NaN would snapshot on EVERY
        # submit; all of these must fall back to the default cadence
        for tok in ("NaN", "Infinity", "-Infinity", "bogus", "true",
                    "[1]", "null"):
            monkeypatch.setenv("PADDLE_TPU_FLEET_SNAPSHOT_EVERY_S", tok)
            mon = fobs.FleetMonitor(_RouterStub())
            assert mon.interval_s == fobs.FleetMonitor.DEFAULT_INTERVAL_S, tok

    def test_accepts_finite_numbers(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLEET_SNAPSHOT_EVERY_S", "2.5")
        assert fobs.FleetMonitor(_RouterStub()).interval_s == 2.5
        monkeypatch.setenv("PADDLE_TPU_FLEET_SNAPSHOT_EVERY_S", "0")
        assert fobs.FleetMonitor(_RouterStub()).interval_s == 0.0


# -- schema tables -------------------------------------------------------

def _journey_rec(**kw):
    rec = {"ts": 1754300000.0, "rank": 0, "kind": "journey",
           "request_id": "r-1", "router": "r",
           "prefill_engine": "r_prefill", "decode_engine": "r_decode",
           "slo_class": "interactive", "outcome": "completed",
           "prompt_tokens": 6, "generated_tokens": 3, "pages_moved": 2,
           "chain_tokens": 7, "page_size": 4, "queue_s": 0.001,
           "prefill_s": 0.02, "handoff_gap_s": 0.0005,
           "decode_s": 0.1, "latency_s": 0.1215, "ttft_s": 0.021,
           "deadline_s": 8.0, "deadline_met": True}
    rec.update(kw)
    return rec


def _fleet_rec(**kw):
    rec = {"ts": 1754300000.0, "rank": 0, "kind": "fleet",
           "router": "r", "fleet": ["r_prefill", "r_decode"],
           "n_engines": 2, "n_pools": 1, "queue_depth": 1, "active": 2,
           "slots_free": 2, "admittable_pages": 40, "free_pages": 44,
           "outstanding_claims": 4, "saturated": [],
           "engines": {"r_prefill": {"queue_depth": 1, "active": 1,
                                     "slots_free": 1},
                       "r_decode": {"queue_depth": 0, "active": 1,
                                    "slots_free": 1}},
           "window_s": 5.0, "arrival_rate": 2.0,
           "completion_rate": 1.8, "handoff_rate": 1.8,
           "rejection_rate": 0.2,
           "slo_attainment": {"interactive": 0.95},
           "requests": 10, "dispatched": 9, "rejected": 1,
           "handoffs": 9}
    rec.update(kw)
    return rec


class TestJourneySchema:
    def test_good_record_passes(self):
        assert _validate(_journey_rec()) == []

    @pytest.mark.parametrize("bad,needle", [
        # a journey closes at a decode TERMINAL — never at the handoff
        (_journey_rec(outcome="handoff"), "outcome"),
        (_journey_rec(decode_engine="r_prefill"), "prefill_engine"),
        (_journey_rec(slo_class="gold"), "slo_class"),
        (_journey_rec(pages_moved=5), "reconcile"),
        (_journey_rec(latency_s=0.05), "phase"),
        (_journey_rec(handoff_gap_s=-0.1), "handoff_gap_s"),
        (_journey_rec(request_id=""), "request_id"),
        (_journey_rec(deadline_met="yes"), "deadline_met"),
        (_journey_rec(generated_tokens=-1), "generated_tokens"),
        # strategy-conditional payload rules (cache_strategy enum)
        (_journey_rec(cache_strategy="magnetic"), "cache_strategy"),
        # a recurrent chain is ONE state blob: pages never move
        (_journey_rec(cache_strategy="recurrent", pages_moved=2,
                      state_bytes=4096), "state blob"),
        # ... and the blob must have size
        (_journey_rec(cache_strategy="recurrent", pages_moved=0,
                      state_bytes=0), "state_bytes"),
        # hybrid moves pages AND a blob — zero blob bytes is a lie
        (_journey_rec(cache_strategy="hybrid", state_bytes=0),
         "state_bytes"),
        # absent cache_strategy means paged: the ceil rule still bites
        (_journey_rec(pages_moved=5), "reconcile"),
    ])
    def test_rejects_bad_records(self, bad, needle):
        errs = _validate(bad)
        assert errs and any(needle in e for e in errs), (errs, needle)

    def test_recurrent_journey_passes(self):
        rec = _journey_rec(cache_strategy="recurrent", pages_moved=0,
                           state_bytes=4096)
        assert _validate(rec) == []


class TestFleetSchema:
    def test_good_record_passes(self):
        assert _validate(_fleet_rec()) == []

    @pytest.mark.parametrize("bad,needle", [
        (_fleet_rec(n_pools=3), "n_pools"),
        (_fleet_rec(saturated=["ghost"]), "saturated"),
        (_fleet_rec(engines={"ghost": {}}), "engines"),
        (_fleet_rec(slo_attainment={"interactive": 1.5}),
         "slo_attainment"),
        (_fleet_rec(arrival_rate=-1.0), "arrival_rate"),
        (_fleet_rec(router=""), "router"),
        (_fleet_rec(fleet=[]), "fleet"),
    ])
    def test_rejects_bad_records(self, bad, needle):
        errs = _validate(bad)
        assert errs and any(needle in e for e in errs), (errs, needle)


# -- pressure events: the AnomalyDetector discipline ---------------------

class TestFleetPressure:
    def test_saturation_edge_triggered_and_rearmed(self):
        p = fobs.FleetPressure("pr", saturation_snapshots=3)
        sat = {"saturated": ["e0", "e1"]}
        clear = {"saturated": []}
        for rec in (sat, sat):
            p.observe_snapshot(rec)
        assert len(p.events) == 0  # below K: no event yet
        p.observe_snapshot(sat)
        assert [e["event"] for e in p.events] == ["fleet_saturated"]
        for _ in range(5):  # a saturated hour is ONE event
            p.observe_snapshot(sat)
        assert len(p.events) == 1
        p.observe_snapshot(clear)  # re-arm
        for rec in (sat, sat, sat):
            p.observe_snapshot(rec)
        assert [e["event"] for e in p.events] \
            == ["fleet_saturated", "fleet_saturated"]
        assert p.events[-1]["engines"] == ["e0", "e1"]

    def test_gap_spike_never_poisons_its_baseline(self):
        p = fobs.FleetPressure("pr", gap_min_history=5,
                               gap_spike_factor=4.0, gap_floor_s=0.005)
        for _ in range(6):
            p.note_handoff_gap(0.01)  # median 0.01 -> threshold 0.04
        assert len(p.events) == 0
        p.note_handoff_gap(0.5)  # spike
        assert [e["event"] for e in p.events] == ["handoff_gap_spike"]
        assert p.events[-1]["gap_s"] == 0.5
        # the spike was NOT folded into the window: the same value
        # again is still a spike against the unchanged baseline
        p.note_handoff_gap(0.5)
        assert len(p.events) == 1  # ...but edge-triggered: no re-emit
        p.note_handoff_gap(0.01)  # clears -> re-arm
        p.note_handoff_gap(0.5)
        assert [e["event"] for e in p.events] \
            == ["handoff_gap_spike", "handoff_gap_spike"]

    def test_gap_floor_hides_idle_fleet_jitter(self):
        p = fobs.FleetPressure("pr", gap_min_history=3,
                               gap_spike_factor=4.0, gap_floor_s=0.005)
        for _ in range(5):
            p.note_handoff_gap(0.0002)  # µs-scale gaps, idle fleet
        p.note_handoff_gap(0.004)  # 20x the median, under the floor
        assert len(p.events) == 0

    def test_rejection_burst_edge_triggered(self):
        p = fobs.FleetPressure("pr", rejection_burst=5,
                               rejection_window_s=60.0)
        for _ in range(4):
            p.note_rejection()
        assert len(p.events) == 0
        p.note_rejection()  # the 5th inside the window
        assert [e["event"] for e in p.events] == ["rejection_burst"]
        for _ in range(5):  # the storm persists: still one event
            p.note_rejection()
        assert len(p.events) == 1


# -- the wedged-engine drill ---------------------------------------------

class TestWedgedEngine:
    def test_rollup_and_placement_survive_a_stuck_engine(self):
        """One engine's scheduler lock held from outside (the wedge a
        hung decode loop or a fault-injection drill produces): the
        router must keep reporting (the stuck engine degrades to
        `unavailable`), the fleet snapshot must keep emitting, and
        submit must land on the healthy mate."""
        healthy = GenerationEngine(MODEL, n_pages=64, page_size=4,
                                   max_batch=2, max_new_tokens=8,
                                   prefix_cache=False,
                                   name="fo_wedge_ok")
        wedged = GenerationEngine(MODEL, n_pages=64, page_size=4,
                                  max_batch=2, max_new_tokens=8,
                                  prefix_cache=False,
                                  name="fo_wedge_stuck")
        router = ServingRouter([healthy, wedged], name="fo_wedge",
                               fleet_snapshot_s=1000.0)
        # warm the healthy path first so the wedged-phase submit isn't
        # also paying first-compile time
        router.submit(np.arange(1, 5), max_new_tokens=2).result(300)
        # the wedge must come from ANOTHER thread: _cv wraps an RLock,
        # so this thread's own acquire would happily re-enter in
        # load_report below instead of timing out
        grabbed = threading.Event()
        release = threading.Event()

        def hold():
            if wedged._cv.acquire(timeout=30):
                grabbed.set()
                release.wait(120)
                wedged._cv.release()

        holder = threading.Thread(target=hold, daemon=True)
        holder.start()
        assert grabbed.wait(60), "could not wedge the engine under test"
        try:
            # the wedged engine's bounded acquire gives up; the fleet
            # rollup still answers, naming the stuck engine
            fleet = router.load_report()
            assert "unavailable" in fleet["engines"]["fo_wedge_stuck"]
            assert "unavailable" not in fleet["engines"]["fo_wedge_ok"]
            assert "fo_wedge_stuck" in fleet["fleet"]["saturated"]
            assert fleet["fleet"]["n_engines"] == 2
            # the fleet snapshot still emits, schema-valid, carrying
            # the degraded entry
            snap = router._fleet_mon.snapshot()
            assert snap is not None and _validate(snap) == []
            assert "unavailable" in snap["engines"]["fo_wedge_stuck"]
            # placement: the wedged engine scores last-resort, so the
            # request lands on the healthy mate and completes
            h = router.submit(np.arange(1, 6), max_new_tokens=3,
                              deadline_ms=120_000)
            assert h.trace.engine == "fo_wedge_ok"
            assert len(h.result(300)) == 3
        finally:
            release.set()
            holder.join(30)
            router.shutdown()
