"""Statistics ops. Parity: python/paddle/tensor/stat.py."""
import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op
from .math import mean  # re-export for paddle.mean

__all__ = ["mean", "std", "var", "median", "nanmedian", "quantile",
           "nanquantile", "numel"]


def _ax(axis):
    return tuple(axis) if isinstance(axis, (list, tuple)) else axis


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(lambda a: jnp.var(a, axis=_ax(axis),
                                      ddof=1 if unbiased else 0,
                                      keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(lambda a: jnp.std(a, axis=_ax(axis),
                                      ddof=1 if unbiased else 0,
                                      keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.median(a, axis=_ax(axis),
                                         keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.nanmedian(a, axis=_ax(axis),
                                            keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    qv = q.value if isinstance(q, Tensor) else jnp.asarray(q)
    return apply_op(lambda a: jnp.quantile(a, qv, axis=_ax(axis),
                                           keepdims=keepdim,
                                           method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    qv = q.value if isinstance(q, Tensor) else jnp.asarray(q)
    return apply_op(lambda a: jnp.nanquantile(a, qv, axis=_ax(axis),
                                              keepdims=keepdim,
                                              method=interpolation), x)


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))
