"""Runtime converters the rewritten AST dispatches to.

Parity: python/paddle/fluid/dygraph/dygraph_to_static/convert_operators.py:26
(convert_ifelse / convert_while_loop / convert_logical_*). TPU-native
design: instead of building ProgramDesc cond/while blocks, a converted
construct decides AT TRACE TIME whether its condition is a traced tensor —
if so it lowers onto XLA control flow (select for `if`, lax.while_loop /
fori_loop for loops: static shapes, compiler-friendly); otherwise it
executes ordinary Python, preserving eager semantics exactly (including
short-circuiting and non-tensor locals).

Variable plumbing: the AST pass emits `__jst_get_N`/`__jst_set_N` closures
over the enclosing frame's locals (nonlocal-writing), so branch/body
functions mutate locals naturally and the converters can snapshot, re-run,
and select without frame hacking.

`if` lowering note: both branches are executed under the trace and merged
with a per-leaf select (jnp.where) — the jnp.where formulation XLA compiles
cond to anyway when branches are cheap, and the only formulation that
tolerates branches assigning fresh Tensors over Python scalars. Matching
shapes/dtypes across branches are required, as with lax.cond.
"""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor

__all__ = [
    "UNDEFINED", "convert_ifelse", "convert_ifexp", "convert_while_loop",
    "convert_for", "convert_for_range", "convert_logical_and",
    "convert_logical_or", "convert_logical_not", "convert_var_to_bool",
    "convert_call", "not_returned", "convert_assert", "convert_print",
    "range_continues", "seq_continues", "seq_get",
    "materialize_seq",
]


class _Undefined:
    """Sentinel for a name not yet bound when a converted construct starts.
    Reads of it fail loudly (ref: variable_trans_func UndefinedVar)."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<undefined local>"

    def __bool__(self):
        raise NameError(
            "local variable used before assignment inside converted "
            "control flow")


UNDEFINED = _Undefined()


def _is_traced(x):
    v = x.value if isinstance(x, Tensor) else x
    return isinstance(v, jax.core.Tracer)


def _is_tensorish(x):
    return isinstance(x, (Tensor, jax.Array)) or \
        type(x).__name__ == "ArrayImpl"


def _raw(x):
    return x.value if isinstance(x, Tensor) else x


def _pred(c):
    v = c.value if isinstance(c, Tensor) else jnp.asarray(c)
    return jnp.reshape(v, ()).astype(bool)


def _arrs(vals):
    """Tensor leaves -> arrays (tuple positions only, no nesting)."""
    return tuple(v.value if isinstance(v, Tensor) else v for v in vals)


def _tens(vals):
    """array leaves -> Tensors."""
    return tuple(Tensor(v) if hasattr(v, "dtype") and hasattr(v, "shape")
                 else v for v in vals)


def convert_var_to_bool(x):
    if isinstance(x, Tensor):
        if _is_traced(x):
            return x
        return bool(x.numpy().reshape(()))
    return x


def convert_logical_and(lhs, rhs_fn):
    """`a and b` with short-circuit preserved for non-tensor `a`."""
    if _is_tensorish(lhs):
        rhs = rhs_fn()
        if _is_tensorish(rhs):
            return Tensor(jnp.logical_and(_pred(lhs), _pred(rhs)))
        return Tensor(jnp.logical_and(_pred(lhs), bool(rhs)))
    return lhs and rhs_fn()


def convert_logical_or(lhs, rhs_fn):
    if _is_tensorish(lhs):
        rhs = rhs_fn()
        if _is_tensorish(rhs):
            return Tensor(jnp.logical_or(_pred(lhs), _pred(rhs)))
        return Tensor(jnp.logical_or(_pred(lhs), bool(rhs)))
    return lhs or rhs_fn()


def convert_logical_not(x):
    if _is_tensorish(x):
        return Tensor(jnp.logical_not(_pred(x)))
    return not x


def not_returned(flag):
    return convert_logical_not(flag)


def not_interrupted(brk, cont):
    """Guard after a break/continue site inside a converted loop body."""
    return convert_logical_not(convert_logical_or(brk, lambda: cont))


def _select_leaf(pred_arr, tv, fv, name):
    """Merge one carried local across the two branches of a converted if."""
    # identical object / equal value: nothing to select
    if tv is fv:
        return tv
    internal = name.startswith("__jst_")
    missing_t = tv is UNDEFINED or tv is None
    missing_f = fv is UNDEFINED or fv is None
    if (missing_t or missing_f) and not (missing_t and missing_f):
        # transformer-internal slots (__jst_ret before any return fired)
        # may be one-sided: the guard discipline guarantees the dead side
        # is never read, so fill it with zeros of the live side's shape
        live = fv if missing_t else tv
        if internal and _is_tensorish(live):
            la = _raw(live)
            dead = jnp.zeros_like(la)
            ta, fa = (dead, la) if missing_t else (la, dead)
            return Tensor(jnp.where(pred_arr, ta, fa))
        branch = "false" if missing_t else "true"
        raise ValueError(
            f"variable '{name}' is assigned in only the {branch} branch "
            "of a tensor-dependent `if`; both branches must define it")
    t_tensorish = _is_tensorish(tv)
    f_tensorish = _is_tensorish(fv)
    if not (t_tensorish or f_tensorish):
        # equal concrete values stay python (e.g. an untouched local)
        try:
            if tv == fv:
                return tv
        except Exception:
            pass
    scalar = (bool, int, float)
    if t_tensorish or f_tensorish or (
            isinstance(tv, scalar) and isinstance(fv, scalar)):
        # genuinely data-dependent value: select on device. Divergent
        # python scalars (e.g. the early-return flag) tensorize here —
        # that is the honest semantics: their value depends on the traced
        # predicate.
        ta, fa = _raw(tv), _raw(fv)
        ta = jnp.asarray(ta) if not hasattr(ta, "dtype") else ta
        fa = jnp.asarray(fa) if not hasattr(fa, "dtype") else fa
        if ta.shape != fa.shape:
            raise ValueError(
                f"tensor-dependent `if`: variable '{name}' has shape "
                f"{ta.shape} in the true branch but {fa.shape} in the "
                "false branch; graph control flow needs matching shapes")
        return Tensor(jnp.where(pred_arr, ta, fa))
    try:
        if tv == fv:
            return tv
    except Exception:
        pass
    raise ValueError(
        f"variable '{name}' takes different non-tensor values in the two "
        f"branches of a tensor-dependent `if` ({tv!r} vs {fv!r}); a traced "
        "predicate can only select between tensors")


def convert_ifelse(pred, true_fn, false_fn, get_vars, set_vars,
                   var_names=None):
    """Plain Python if for concrete predicates; trace-both-and-select for
    traced ones."""
    pred = convert_var_to_bool(pred)
    if not _is_tensorish(pred):
        (true_fn if pred else false_fn)()
        return
    if not _is_traced(pred):
        (true_fn if bool(jax.device_get(_pred(pred))) else false_fn)()
        return

    snapshot = _arrs(get_vars())  # immutable arrays / python objects

    set_vars(_tens(snapshot))
    true_fn()
    tvals = get_vars()
    set_vars(_tens(snapshot))
    false_fn()
    fvals = get_vars()

    p = _pred(pred)
    names = var_names or [f"#{i}" for i in range(len(tvals))]
    merged = tuple(_select_leaf(p, tv, fv, n)
                   for tv, fv, n in zip(tvals, fvals, names))
    set_vars(merged)


def convert_ifexp(pred, true_fn, false_fn):
    """`a if c else b` expression form."""
    pred = convert_var_to_bool(pred)
    if not _is_tensorish(pred):
        return true_fn() if pred else false_fn()
    if not _is_traced(pred):
        return true_fn() if bool(jax.device_get(_pred(pred))) \
            else false_fn()
    tv, fv = true_fn(), false_fn()
    return _select_leaf(_pred(pred), tv, fv, "<ifexp>")


def _type_undefined_carry(carry0, body_fn, get_vars, set_vars, kind):
    """Loop-local vars (assigned inside the body, unbound before the loop)
    enter the lax carry as UNDEFINED — lax needs a typed value. Run the
    body ONCE speculatively at the current trace level to learn their
    types, seed them with zeros of that type, and let XLA dead-code-
    eliminate the speculative ops. A read-before-write of such a var
    inside the speculative run still hits the UNDEFINED sentinel and
    fails loudly (matching Python's UnboundLocalError discipline)."""
    if not any(v is UNDEFINED for v in carry0):
        return carry0
    body_fn()
    probed = get_vars()
    seeded = []
    for v0, pv in zip(carry0, probed):
        if v0 is not UNDEFINED:
            seeded.append(v0)
        elif _is_tensorish(pv):
            seeded.append(Tensor(jnp.zeros_like(_raw(pv))))
        elif pv is UNDEFINED:
            raise ValueError(
                f"a loop-local variable is never assigned on some path "
                f"through this converted `{kind}` body; define it before "
                "the loop")
        else:
            seeded.append(pv)
    seeded = tuple(seeded)
    set_vars(seeded)
    return seeded


def _carryable(v):
    return _is_tensorish(v) or isinstance(v, (bool, int, float)) \
        or v is UNDEFINED


def _subset_accessors(get_vars, set_vars, idx):
    """get/set restricted to carry positions `idx`; other locals stay
    whatever the (traced-once) body last bound them to — they are
    non-tensor, so they cannot be data-dependent anyway."""
    def sub_get():
        full = get_vars()
        return tuple(full[i] for i in idx)

    def sub_set(vals):
        full = list(get_vars())
        for i, v in zip(idx, vals):
            full[i] = v
        set_vars(tuple(full))
    return sub_get, sub_set


def convert_while_loop(cond_fn, body_fn, get_vars, set_vars):
    """Runs as an ordinary Python while as long as the condition is
    concrete (each such iteration simply unrolls under a trace, exactly
    like round-3 trace-only behavior); the moment the condition becomes a
    traced value, the REMAINING iterations lower onto one lax.while_loop.
    Non-arrayable locals (str/list/None...) never enter the lax carry —
    they keep their traced-body binding, which is sound because a
    non-tensor value cannot depend on traced data."""
    while True:
        c = cond_fn()
        if _is_tensorish(c) and _is_traced(c):
            break
        if not convert_var_to_bool(c):
            return
        body_fn()

    full0 = _type_undefined_carry(get_vars(), body_fn, get_vars,
                                  set_vars, "while")
    idx = tuple(i for i, v in enumerate(full0) if _carryable(v))
    get_c, set_c = _subset_accessors(get_vars, set_vars, idx)
    carry0 = get_c()

    def _cond(carry):
        set_c(_tens(carry))
        return _pred(cond_fn())

    def _body(carry):
        set_c(_tens(carry))
        body_fn()
        return _arrs(get_c())

    out = jax.lax.while_loop(_cond, _body, _arrs(carry0))
    set_c(_tens(out))


def convert_for(iterable, target_set, body_fn, get_vars, set_vars):
    """`for <tgt> in <iterable>:` — ordinary Python iteration for concrete
    iterables (a trace unrolls it); ONE lax.fori_loop over the leading dim
    for a traced Tensor (no unroll, compile time stays flat)."""
    if not (isinstance(iterable, Tensor) and _is_traced(iterable)):
        if isinstance(iterable, Tensor):
            for i in range(iterable.shape[0]):
                target_set(iterable[i])
                body_fn()
            return
        for item in iterable:
            target_set(item)
            body_fn()
        return

    arr = iterable.value
    # bind the target to a typed prototype BEFORE capturing the carry so
    # its slot is not UNDEFINED, then type any other loop-locals
    target_set(Tensor(jax.lax.dynamic_index_in_dim(
        arr, 0, axis=0, keepdims=False)))
    full0 = _type_undefined_carry(get_vars(), body_fn, get_vars,
                                  set_vars, "for")
    idx = tuple(i for i, v in enumerate(full0) if _carryable(v))
    get_c, set_c = _subset_accessors(get_vars, set_vars, idx)
    carry0 = get_c()

    def _body(i, carry):
        set_c(_tens(carry))
        target_set(Tensor(jax.lax.dynamic_index_in_dim(
            arr, i, axis=0, keepdims=False)))
        body_fn()
        return _arrs(get_c())

    out = jax.lax.fori_loop(0, arr.shape[0], _body, _arrs(carry0))
    set_c(_tens(out))


def convert_for_range(range_args, target_set, body_fn, get_vars, set_vars):
    """`for i in range(...)` where a bound may be a traced tensor: lowers
    to lax.while_loop with the counter in the carry. Concrete bounds run
    the ordinary Python range loop (trace unrolls it)."""
    args = [a.value if isinstance(a, Tensor) else a for a in range_args]
    if len(args) == 1:
        start, stop, step = 0, args[0], 1
    elif len(args) == 2:
        start, stop, step = args[0], args[1], 1
    else:
        start, stop, step = args[:3]
    if not any(isinstance(a, jax.core.Tracer) for a in (start, stop, step)):
        for i in range(int(start) if hasattr(start, "dtype") else start,
                       int(stop) if hasattr(stop, "dtype") else stop,
                       int(step) if hasattr(step, "dtype") else step):
            target_set(i)
            body_fn()
        return

    i0 = jnp.asarray(start, jnp.int32)
    stop_a = jnp.asarray(stop, jnp.int32)
    step_a = jnp.asarray(step, jnp.int32)
    target_set(Tensor(i0))
    full0 = _type_undefined_carry(get_vars(), body_fn, get_vars,
                                  set_vars, "for")
    idx = tuple(i for i, v in enumerate(full0) if _carryable(v))
    get_c, set_c = _subset_accessors(get_vars, set_vars, idx)
    carry0 = get_c()

    def _cond(c):
        i = c[0]
        return jnp.where(step_a > 0, i < stop_a, i > stop_a)

    def _body(c):
        i, carry = c
        set_c(_tens(carry))
        target_set(Tensor(i))
        body_fn()
        return (i + step_a, _arrs(get_c()))

    _, out = jax.lax.while_loop(_cond, _body, (i0, _arrs(carry0)))
    set_c(_tens(out))


# ---------------------------------------------------------------- calls
_NEVER_CONVERT_MODULE_PREFIXES = (
    "paddle_tpu", "jax", "jaxlib", "numpy", "builtins", "math", "functools",
    "itertools", "collections", "typing", "torch", "flax", "optax",
)


def convert_call(fn):
    """Recursively convert user callees so their control flow converts too
    (ref convert_call, convert_operators.py:26). Framework / library
    callables pass through; any conversion failure falls back to the
    original callable (reference behavior: warn-and-fallback)."""
    from .program_translator import convert_to_static, conversion_enabled

    if not conversion_enabled():
        return fn
    try:
        if getattr(fn, "_not_to_static", False):
            return fn
        if getattr(fn, "__paddle_tpu_converted__", False):
            return fn
        if not callable(fn) or isinstance(fn, type):
            return fn
        from ...nn.layer.layers import Layer
        if isinstance(fn, Layer):
            return fn  # sub-layer calls keep eager-trace semantics; the
            # layer's own forward converts when it goes through to_static
        code = getattr(fn, "__code__", None)
        if code is None:
            return fn
        mod = getattr(fn, "__module__", "") or ""
        if mod.split(".")[0] in _NEVER_CONVERT_MODULE_PREFIXES:
            return fn
        return convert_to_static(fn)
    except Exception:
        return fn


def convert_assert(cond, *msg):
    """`assert` inside converted code (ref convert_operators.convert_assert
    -> Assert op). Concrete conditions keep Python semantics; a traced
    condition cannot halt tracing, so it lowers to a device-side
    checkify-style debug check (prints on failure, does not abort —
    matching the reference Assert op's deferred-runtime nature)."""
    if isinstance(cond, Tensor) and _is_traced(cond):
        ok = jnp.all(jnp.asarray(_raw(cond)))  # any shape, like the
        # concrete path's .all()

        if msg and isinstance(msg[0], Tensor):
            mv = msg[0].value

            def _report():
                jax.debug.print("Assertion failed: {m}", m=mv)
        else:
            # static message: brace-escape so str.format never sees it
            text = ("Assertion failed" +
                    (": " + str(msg[0]).replace("{", "{{")
                     .replace("}", "}}") if msg else ""))

            def _report():
                jax.debug.print(text)
        # print ONLY on failure (deferred runtime check)
        jax.lax.cond(ok, lambda: None, _report)
        return
    if isinstance(cond, Tensor):
        cond = bool(cond.numpy().reshape(())) if cond.size == 1 \
            else bool(cond.numpy().all())
    assert cond, (msg[0] if msg else "")


def convert_print(*args, **kwargs):
    """`print` inside converted code (ref convert_operators.convert_print
    -> Print op): traced tensors print their runtime VALUES via
    jax.debug.print instead of tracer reprs. sep/end are honored; file/
    flush cannot be (the print happens device-side at run time)."""
    if any(isinstance(a, Tensor) and _is_traced(a) for a in args):
        if kwargs.get("file") is not None:
            import warnings
            warnings.warn("print(file=...) is ignored for traced tensors "
                          "(device-side jax.debug.print)")
        sep = kwargs.get("sep", " ")
        end = kwargs.get("end", "")

        def esc(x):
            return str(x).replace("{", "{{").replace("}", "}}")

        parts, values, vi = [], {}, 0
        for a in args:
            if isinstance(a, Tensor):
                key = f"v{vi}"
                vi += 1
                parts.append("{" + key + "}")
                values[key] = a.value
            else:
                parts.append(esc(a))
        # a non-default `end` is appended (debug.print still emits its
        # own trailing newline — device-side prints are line-based)
        jax.debug.print(esc(sep).join(parts) + esc(end), **values)
        return
    print(*[a.numpy() if isinstance(a, Tensor) else a for a in args],
          **kwargs)


def range_continues(i, stop, step):
    """Loop test for a for-range desugared to while (interrupt support):
    sign-aware, tensor-aware."""
    ti = _is_tensorish(i) or _is_tensorish(stop) or _is_tensorish(step)
    if not ti:
        return i < stop if step > 0 else i > stop
    iv, sv, st = (_raw(i), _raw(stop), _raw(step))
    return Tensor(jnp.where(jnp.asarray(st) > 0,
                            jnp.asarray(iv) < jnp.asarray(sv),
                            jnp.asarray(iv) > jnp.asarray(sv)))


def materialize_seq(it):
    """Normalize a for-iterable for the interrupt desugar: Tensors and
    integer-indexable sequences (list/tuple/range/str) pass through;
    everything else (zip, generators, dict/set/dict-views, loaders)
    materializes to a list — iteration ORDER semantics are preserved
    (a dict materializes to its keys), and the counter-while can index
    the result."""
    if isinstance(it, Tensor) or isinstance(it, (list, tuple, range, str)):
        return it
    return list(it)


def seq_continues(i, seq):
    """Loop test for a for-over-sequence desugared to while."""
    n = seq.shape[0] if isinstance(seq, Tensor) else len(seq)
    if _is_tensorish(i):
        return Tensor(jnp.asarray(_raw(i)) < n)
    return i < n


def seq_get(seq, i):
    """Indexed access for the desugared for: Tensors accept a traced
    index; a PYTHON sequence cannot be indexed by a traced counter (the
    loop went data-dependent) — fail with guidance instead of a cryptic
    list-index TypeError."""
    if isinstance(seq, Tensor):
        return seq[i]
    if _is_tensorish(i):
        if _is_traced(i):
            raise TypeError(
                "a `for` over a python sequence became data-dependent "
                "(its break/continue condition is traced); stack the "
                "sequence into one Tensor so the loop can lower to lax")
        i = int(jnp.asarray(_raw(i)))
    return seq[i]
