"""Pooling layers. Parity: python/paddle/nn/layer/pooling.py."""
from .. import functional as F
from .layers import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D", "MaxUnPool2D", "MaxUnPool1D", "MaxUnPool3D"]


class _PoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}


class AvgPool1D(_PoolNd):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class AvgPool2D(_PoolNd):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class AvgPool3D(_PoolNd):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class MaxPool1D(_PoolNd):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class MaxPool2D(_PoolNd):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class MaxPool3D(_PoolNd):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class _AdaptivePoolNd(Layer):
    def __init__(self, output_size, **kwargs):
        super().__init__()
        self.output_size = output_size
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}


class AdaptiveAvgPool1D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, **self.kwargs)


class AdaptiveAvgPool3D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, **self.kwargs)


class AdaptiveMaxPool1D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, **self.kwargs)


class AdaptiveMaxPool2D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, **self.kwargs)


class AdaptiveMaxPool3D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, **self.kwargs)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, output_size=self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, output_size=self.output_size)
