"""Pluggable decode-cache strategies for the serving stack.

The continuous-batching engine (inference/serving.py), the router
(inference/frontdoor.py), and both observatories were written against
`ops.paged_attention.PagedKVCache` — but everything they actually call
is a narrow allocator/ledger surface, not attention-specific at all:

    admission accounting   pages_needed / can_allocate / set_claim /
                           outstanding_claims (a generic cost+claims
                           ledger; "pages" is just the cost unit)
    sequence lifecycle     add_sequence / free_sequence / length /
                           advance / rollback / pages_held
    prefix cache           match_prefix(_credit) / acquire_prefix /
                           register_prefix (may be inert)
    disaggregation         export_chain / adopt_chain / release_chain
    telemetry              pool_stats / shared_page_count / n_pages /
                           n_free_pages / n_evictable_pages / page_size

This module names that surface a CACHE STRATEGY and adds the second
implementation the SSM family needs (PAPERS.md "Compiler-First State
Space Duality and Portable O(1) Autoregressive Caching"):

    PagedKVCache         strategy "paged"     cost = ceil(tokens/P)
    RecurrentStateCache  strategy "recurrent" cost = 1 slot, O(1) in
                         sequence length — a fixed-size state blob
                         (conv tail + SSM state matrix) per sequence
    HybridCache          strategy "hybrid"    both ledgers at once for
                         models interleaving SSM and attention layers

`strategy_of(cache)` is how the engine/router/schema stamp records;
every strategy's `pool_stats()` carries its own `cache_strategy` so
the kvcache telemetry self-describes (tools/check_metrics_schema.py
validates the strategy-conditional shape).
"""
import itertools
import threading

import numpy as np
import jax.numpy as jnp

__all__ = ["strategy_of", "RecurrentChainHandle", "RecurrentStateCache",
           "HybridChainHandle", "HybridCache"]


def strategy_of(cache):
    """The cache's strategy name ("paged" | "recurrent" | "hybrid").
    Defaults to "paged" for strategy-unaware caches (duck-typed
    test doubles, older pools)."""
    return str(getattr(cache, "strategy", "paged"))


_CHAIN_IDS = itertools.count()


class RecurrentChainHandle:
    """A detached SSM decode state in flight between two sequences —
    the recurrent strategy's handoff unit, duck-compatible with
    `ops.paged_attention.KVChainHandle` (same ledger fields, same
    journey-telemetry riders) except that what moves is ONE fixed-size
    state blob per layer instead of a page-id list: `pages` is always
    empty, `state_bytes` is the blob's size. While the handle is live
    the pool counts its slot claim in `outstanding_claims()`, so the
    handoff window cannot be double-booked. Consume exactly once via
    `adopt_chain` (same pool) or `release_chain`."""

    __slots__ = ("chain_id", "pages", "length", "drawn", "claim",
                 "consumed", "request_id", "t_export", "draft_chain",
                 "conv_state", "ssm_state", "state_bytes")

    strategy = "recurrent"

    def __init__(self, length, claim, conv_state, ssm_state,
                 state_bytes):
        self.chain_id = next(_CHAIN_IDS)
        self.pages = ()          # no pages move — the blob is the chain
        self.length = length
        # the slot was FREED at export (the state left the pool as a
        # blob); drawn=0 against claim=1 keeps one slot reserved in
        # outstanding_claims() for re-adoption — the same limbo
        # accounting the paged chain gets from its held pages
        self.drawn = 0
        self.claim = claim
        self.consumed = False
        self.request_id = None
        self.t_export = None
        self.draft_chain = None
        self.conv_state = conv_state    # [L, d_conv-1, d_inner]
        self.ssm_state = ssm_state      # [L, d_inner, d_state]
        self.state_bytes = state_bytes


class RecurrentStateCache:
    """Host-side slot allocator + device-side per-layer state pools
    for the SSM decode cache: each sequence owns ONE fixed-size slot
    regardless of its length — a conv tail [d_conv-1, d_inner] and an
    SSM state [d_inner, d_state] per layer. Admission cost is the
    constant 1, so `pages_needed` (kept under the historical name the
    engine calls — the unit here is SLOTS) never grows with
    prompt + max_new_tokens: the O(1) capacity play.

    Slot 0 is reserved as the pad slot (pad rows of the fixed-shape
    serving step gather/scatter it harmlessly), mirroring the paged
    pool's reserved page 0 — so `n_pages` (= n_slots + 1) keeps the
    engine's `usable = n_pages - 1` arithmetic exact. The prefix-cache
    surface is inert (a recurrent state at a page boundary is not
    addressable the way KV pages are): match/acquire/register all
    report misses."""

    strategy = "recurrent"

    def __init__(self, n_layers, n_slots, d_inner, d_state, d_conv,
                 dtype=jnp.float32, page_size=16):
        self.n_layers = int(n_layers)
        self.n_slots = int(n_slots)
        if self.n_slots < 1:
            raise ValueError("RecurrentStateCache needs n_slots >= 1")
        self.n_pages = self.n_slots + 1   # slot 0 = reserved pad slot
        self.page_size = int(page_size)   # token bucketing only — no
        # memory meaning here; the engine's warm/step token math and
        # the route records still quote it
        self.d_inner = int(d_inner)
        self.d_state = int(d_state)
        self.d_conv = int(d_conv)
        self.dtype = dtype
        S = self.n_pages
        self.conv = [jnp.zeros((S, self.d_conv - 1, self.d_inner),
                               dtype) for _ in range(self.n_layers)]
        self.ssm = [jnp.zeros((S, self.d_inner, self.d_state), dtype)
                    for _ in range(self.n_layers)]
        # same role as PagedKVCache.lock: serializes the host
        # allocator + the donated-pool swap across engines
        self.lock = threading.RLock()
        self._free = list(range(1, S))
        self._slot = {}    # seq_id -> slot
        self._len = {}     # seq_id -> tokens consumed so far
        self._claims = {}  # seq_id -> slots reserved at admission
        self._chains = {}  # chain_id -> in-flight RecurrentChainHandle
        self._stats = {"slots_drawn": 0}

    def device_arrays(self):
        """The pool's live device arrays (per-layer conv and ssm state
        pools) — the memory observatory's attribution surface."""
        return list(self.conv) + list(self.ssm)

    # ---- geometry ----------------------------------------------------
    def state_bytes_per_slot(self):
        """Bytes of ONE sequence's decode state — the O(1) constant
        the capacity comparison vs paged KV is about."""
        per_layer = ((self.d_conv - 1) * self.d_inner
                     + self.d_inner * self.d_state)
        return int(self.n_layers * per_layer
                   * np.dtype(self.dtype).itemsize)

    def exec_signature(self):
        """Pool-geometry component of the serving executable's cache
        key (ssm.warm_ragged) — two engines over one model with
        different pools must not collide on compiled programs."""
        return ("recurrent", self.n_pages, self.d_inner, self.d_state,
                self.d_conv,
                str(self.conv[0].dtype) if self.conv else "poisoned")

    # ---- allocator ----------------------------------------------------
    def add_sequence(self, seq_id):
        if seq_id in self._slot:
            raise ValueError(f"sequence {seq_id!r} already present")
        if not self._free:
            raise RuntimeError(
                "RecurrentStateCache out of state slots — free "
                "finished sequences or grow n_slots")
        self._slot[seq_id] = self._free.pop()
        self._len[seq_id] = 0
        self._stats["slots_drawn"] += 1

    def free_sequence(self, seq_id):
        self._free.append(self._slot.pop(seq_id))
        self._len.pop(seq_id)
        self._claims.pop(seq_id, None)

    def length(self, seq_id):
        return self._len[seq_id]

    def slot(self, seq_id):
        return self._slot[seq_id]

    def advance(self, seq_id, n_tokens):
        self._len[seq_id] += n_tokens

    def rollback(self, seq_id, n_tokens):
        """Recurrent state folds every consumed token into one blob —
        there is nothing to un-commit, so speculative rejection cannot
        run on this strategy (the engine refuses the combination at
        construction)."""
        if int(n_tokens) > 0:
            raise RuntimeError(
                "recurrent decode state is not rewindable — "
                "speculative decoding requires the paged strategy")

    # ---- admission ledger (slot units under the page-era names) ------
    def pages_needed(self, n_tokens):
        """Admission cost of a fresh sequence: one slot, whatever the
        token count — the recurrent strategy's defining constant."""
        return 1

    def pages_held(self, seq_id):
        self._slot[seq_id]  # KeyError on unknown, like the paged pool
        return 1

    def n_free_pages(self):
        return len(self._free)

    def n_evictable_pages(self):
        return 0   # no best-effort retention to reclaim

    def shared_page_count(self):
        return 0   # slots are never shared

    def can_allocate(self, n_tokens, reserved=0):
        return 1 + int(reserved) <= len(self._free)

    def set_claim(self, seq_id, n_pages):
        if seq_id not in self._slot:
            raise KeyError(f"set_claim: unknown sequence {seq_id!r}")
        self._claims[seq_id] = int(n_pages)

    def outstanding_claims(self):
        """Slots admission promised but the pool has not handed out:
        a live sequence draws its slot AT admission (add_sequence), so
        only in-flight exported chains — whose slots were freed with
        the state blob detached — contribute."""
        out = sum(max(c - 1, 0) for s, c in list(self._claims.items())
                  if s in self._slot)
        out += sum(max(h.claim - h.drawn, 0)
                   for h in list(self._chains.values()))
        return out

    # ---- prefix cache (inert) ----------------------------------------
    def match_prefix(self, token_ids, max_tokens=None):
        return 0, 0

    def match_prefix_credit(self, token_ids, max_tokens=None):
        return 0, 0, 0

    def acquire_prefix(self, seq_id, token_ids, max_tokens=None):
        return 0

    def register_prefix(self, seq_id, token_ids):
        return None

    # ---- chain handoff (prefill/decode disaggregation) ----------------
    def export_chain(self, seq_id):
        """Detach a sequence's decode state into a RecurrentChainHandle:
        the per-layer state rows are gathered into ONE blob pair, the
        slot returns to the free list, and the handle's claim keeps one
        slot reserved (outstanding_claims) for re-adoption. No token is
        recomputed — the blob IS the whole history."""
        slot = self._slot.pop(seq_id)
        conv_blob = jnp.stack([pool[slot] for pool in self.conv])
        ssm_blob = jnp.stack([pool[slot] for pool in self.ssm])
        handle = RecurrentChainHandle(
            length=self._len.pop(seq_id),
            claim=max(self._claims.pop(seq_id, 1), 1),
            conv_state=conv_blob, ssm_state=ssm_blob,
            state_bytes=self.state_bytes_per_slot())
        self._free.append(slot)
        self._chains[handle.chain_id] = handle
        return handle

    def adopt_chain(self, seq_id, chain):
        """Attach an exported state blob to a FRESH sequence id on the
        SAME pool: allocate a slot (the chain's reserved claim
        guarantees one), scatter the blob back in, resume the claim.
        Returns the adopted token length."""
        if chain.consumed:
            raise ValueError("adopt_chain: chain handle already "
                             "consumed (adopted or released)")
        if self._chains.pop(chain.chain_id, None) is None:
            raise ValueError(
                "adopt_chain: chain was not exported from THIS pool — "
                "share the RecurrentStateCache between the two engines "
                "instead")
        if seq_id in self._slot:
            raise ValueError(f"adopt_chain: sequence {seq_id!r} "
                             "already present")
        chain.consumed = True
        self.add_sequence(seq_id)
        slot = self._slot[seq_id]
        for l in range(self.n_layers):
            self.conv[l] = self.conv[l].at[slot].set(
                chain.conv_state[l].astype(self.conv[l].dtype))
            self.ssm[l] = self.ssm[l].at[slot].set(
                chain.ssm_state[l].astype(self.ssm[l].dtype))
        self._len[seq_id] = chain.length
        if chain.claim:
            self._claims[seq_id] = chain.claim
        return chain.length

    def release_chain(self, chain):
        if chain.consumed:
            return
        chain.consumed = True
        self._chains.pop(chain.chain_id, None)

    # ---- telemetry ----------------------------------------------------
    def pool_stats(self):
        """The pool observatory's snapshot (`kind:"kvcache"` record via
        profiler/serve_observatory.record_pool_stats). Strategy-shaped:
        SLOT gauges plus the per-sequence state-blob size — no page
        fields at all, which is exactly what the schema's recurrent
        branch checks. Snapshot-copies (C-level dict()/list()) make it
        callable from any thread."""
        held = len(dict(self._slot))
        return {
            "cache_strategy": "recurrent",
            "n_slots": int(self.n_slots),
            "free_slots": len(list(self._free)),
            "held_slots": held,
            "sequences": held,
            "slots_drawn": int(self._stats["slots_drawn"]),
            "state_bytes": self.state_bytes_per_slot(),
            "state_bytes_total": self.state_bytes_per_slot()
            * int(self.n_slots),
        }

    # ---- serving-step plan -------------------------------------------
    def plan_step(self, rows, pad_to_tokens=None, pad_to_rows=None):
        """HOST-side (numpy) plan for one fixed-shape ragged SSM step
        over mixed rows (`rows` = [(seq_id, n_tokens)]; decode rows
        carry 1, prefill-chunk rows a prompt slice). Shapes depend
        only on (T, B) = (pad_to_tokens, pad_to_rows), so a serving
        executable keyed on them stays one executable:

            positions [T]  absolute position of each token (sampling
                           keys + hybrid wpe)
            token_seq [T]  owning ROW of each token (pads -> row 0 —
                           harmless: their dt is masked to identity)
            chunk_pos [T]  index of the token within its row's chunk
                           (the conv window's new/saved boundary)
            tok_valid [T]  f32 1/0 — zeroes dt on pads in the step
            slot_ids  [B]  state-pool slot per row (pads -> slot 0)
            row_end   [B]  one past the row's last token in the stream
            row_len   [B]  real tokens the row contributes
            out_idx   [B]  each row's LAST token (next-token readout)
            n_rows         real row count (host slicing)
        """
        n_real = len(rows)
        t_real = sum(int(n) for _, n in rows)
        T = int(pad_to_tokens) if pad_to_tokens else max(t_real, 1)
        B = int(pad_to_rows) if pad_to_rows else max(n_real, 1)
        if t_real > T or n_real > B:
            raise ValueError(
                f"plan_step: {t_real} tokens / {n_real} rows exceed "
                f"padded shape ({T}, {B})")
        i32 = np.int32
        positions = np.zeros((T,), i32)
        token_seq = np.zeros((T,), i32)
        chunk_pos = np.zeros((T,), i32)
        tok_valid = np.zeros((T,), np.float32)
        slot_ids = np.zeros((B,), i32)
        row_end = np.zeros((B,), i32)
        row_len = np.zeros((B,), i32)
        out_idx = np.zeros((B,), i32)
        off = 0
        for r, (sid, n) in enumerate(rows):
            n = int(n)
            start = self._len[sid]
            positions[off:off + n] = start + np.arange(n, dtype=i32)
            token_seq[off:off + n] = r
            chunk_pos[off:off + n] = np.arange(n, dtype=i32)
            tok_valid[off:off + n] = 1.0
            slot_ids[r] = self._slot[sid]
            row_len[r] = n
            off += n
            row_end[r] = off
            out_idx[r] = off - 1
        return {"positions": positions, "token_seq": token_seq,
                "chunk_pos": chunk_pos, "tok_valid": tok_valid,
                "slot_ids": slot_ids, "row_end": row_end,
                "row_len": row_len, "out_idx": out_idx,
                "n_rows": n_real}


class HybridChainHandle:
    """Handoff unit of the hybrid strategy: the paged sub-chain (page
    ids) and the recurrent sub-chain (state blob) move as ONE unit.
    Ledger fields mirror the paged chain (pages/claim/drawn are the
    page-side numbers — the dominant, length-proportional cost);
    `state_bytes` rides from the recurrent side."""

    __slots__ = ("chain_id", "pages", "length", "drawn", "claim",
                 "consumed", "request_id", "t_export", "draft_chain",
                 "paged_chain", "rec_chain", "state_bytes")

    strategy = "hybrid"

    def __init__(self, paged_chain, rec_chain):
        self.chain_id = next(_CHAIN_IDS)
        self.pages = paged_chain.pages
        self.length = paged_chain.length
        self.drawn = paged_chain.drawn
        self.claim = paged_chain.claim
        self.consumed = False
        self.request_id = None
        self.t_export = None
        self.draft_chain = None
        self.paged_chain = paged_chain
        self.rec_chain = rec_chain
        self.state_bytes = rec_chain.state_bytes


class HybridCache:
    """Both ledgers at once for models interleaving SSM and attention
    layers: a PagedKVCache over the ATTENTION layers and a
    RecurrentStateCache over the SSM layers, admitted together (a
    sequence needs its worst-case pages AND one state slot), exported
    together (HybridChainHandle), freed together. One lock object
    covers the pair — the engine's lock discipline (plan through
    donated-pool swap) spans both pools in one acquire.

    Admission accounting is page-denominated (the length-proportional
    side dominates and keeps the router's page math meaningful); the
    slot side is a secondary gate in can_allocate. With
    n_slots = n_pages - 1 the slot pool can never be the binding
    constraint before pages are, so the page-only outstanding_claims
    stays a safe reservation bound. The prefix surface is inert: KV
    pages at a prefix boundary are addressable but the SSM state there
    was never saved, so a hybrid prefix hit cannot be honored."""

    strategy = "hybrid"

    def __init__(self, paged, recurrent):
        self.paged = paged
        self.recurrent = recurrent
        self.lock = paged.lock
        self.recurrent.lock = paged.lock   # one lock for the pair
        self.n_pages = paged.n_pages
        self.page_size = paged.page_size

    def exec_signature(self):
        return (("hybrid", self.paged.n_pages, self.paged.page_size,
                 str(self.paged.k[0].dtype) if self.paged.k
                 else "poisoned")
                + self.recurrent.exec_signature())

    # ---- allocator / ledger ------------------------------------------
    def add_sequence(self, seq_id):
        self.paged.add_sequence(seq_id)
        try:
            self.recurrent.add_sequence(seq_id)
        except Exception:
            self.paged.free_sequence(seq_id)
            raise

    def free_sequence(self, seq_id):
        self.paged.free_sequence(seq_id)
        self.recurrent.free_sequence(seq_id)

    def length(self, seq_id):
        return self.paged.length(seq_id)

    def advance(self, seq_id, n_tokens):
        self.paged.advance(seq_id, n_tokens)
        self.recurrent.advance(seq_id, n_tokens)

    def rollback(self, seq_id, n_tokens):
        # the paged half could rewind, the recurrent half cannot —
        # the pair inherits the stricter contract
        self.recurrent.rollback(seq_id, n_tokens)

    def pages_needed(self, n_tokens):
        return self.paged.pages_needed(n_tokens)

    def pages_held(self, seq_id):
        return self.paged.pages_held(seq_id)

    def n_free_pages(self):
        return self.paged.n_free_pages()

    def n_evictable_pages(self):
        return self.paged.n_evictable_pages()

    def shared_page_count(self):
        return self.paged.shared_page_count()

    def can_allocate(self, n_tokens, reserved=0):
        return (self.paged.can_allocate(n_tokens, reserved=reserved)
                and self.recurrent.can_allocate(n_tokens))

    def set_claim(self, seq_id, n_pages):
        self.paged.set_claim(seq_id, n_pages)

    def outstanding_claims(self):
        return self.paged.outstanding_claims()

    # ---- prefix cache (inert — see class doc) ------------------------
    def match_prefix(self, token_ids, max_tokens=None):
        return 0, 0

    def match_prefix_credit(self, token_ids, max_tokens=None):
        return 0, 0, 0

    def acquire_prefix(self, seq_id, token_ids, max_tokens=None):
        return 0

    def register_prefix(self, seq_id, token_ids):
        return None

    # ---- chain handoff -----------------------------------------------
    def export_chain(self, seq_id):
        pc = self.paged.export_chain(seq_id)
        rc = self.recurrent.export_chain(seq_id)
        return HybridChainHandle(pc, rc)

    def adopt_chain(self, seq_id, chain):
        if chain.consumed:
            raise ValueError("adopt_chain: chain handle already "
                             "consumed (adopted or released)")
        n = self.paged.adopt_chain(seq_id, chain.paged_chain)
        self.recurrent.adopt_chain(seq_id, chain.rec_chain)
        chain.consumed = True
        return n

    def release_chain(self, chain):
        if chain.consumed:
            return
        chain.consumed = True
        self.paged.release_chain(chain.paged_chain)
        self.recurrent.release_chain(chain.rec_chain)

    # ---- serving-step plans ------------------------------------------
    def plan_ragged(self, rows, pad_to_tokens=None, pad_to_rows=None,
                    q_heads=None):
        return self.paged.plan_ragged(rows, pad_to_tokens=pad_to_tokens,
                                      pad_to_rows=pad_to_rows,
                                      q_heads=q_heads)

    def plan_step(self, rows, pad_to_tokens=None, pad_to_rows=None):
        return self.recurrent.plan_step(rows,
                                        pad_to_tokens=pad_to_tokens,
                                        pad_to_rows=pad_to_rows)

    # ---- telemetry ----------------------------------------------------
    def device_arrays(self):
        """Both halves' live device arrays — the memory observatory's
        attribution surface (the halves also register under their own
        tags; mem_report() dedups shared buffers by identity)."""
        return self.paged.device_arrays() + self.recurrent.device_arrays()

    def pool_stats(self):
        """Paged pool snapshot plus the slot/state gauges and the
        hybrid strategy stamp — the schema's hybrid branch = paged
        rules + state_bytes > 0."""
        stats = self.paged.pool_stats()
        rec = self.recurrent.pool_stats()
        stats["cache_strategy"] = "hybrid"
        stats["n_slots"] = rec["n_slots"]
        stats["free_slots"] = rec["free_slots"]
        stats["held_slots"] = rec["held_slots"]
        stats["state_bytes"] = rec["state_bytes"]
        stats["state_bytes_total"] = rec["state_bytes_total"]
        return stats
