"""The compilation observatory (ISSUE 6): per-executable compile/HLO
ledger, retrace forensics, and the ratcheting fusion + compile-budget
gates.

Proof points:
- every AOT-compiled executable emits exactly ONE `kind:"compile"`
  record per distinct signature (per-step, run_steps, accumulate,
  serving buckets; inspection paths add none), with HLO stats
  populated, and the records pass tools/check_metrics_schema.py;
- a forced retrace emits a structured `kind:"event"` naming the
  offending argument and the nature of the change, for each of
  shape / dtype / static-value;
- a persistent-cache-hit run (subprocess pair sharing a cache dir)
  records cache_hit=True, near-zero compile_s, and zero new on-disk
  entries;
- tools/check_compile_budget.py and tools/check_fusion.py run green
  against the checked-in BASELINE_HLO.json and fail (nonzero, naming
  the executable) on an injected regression;
- flight-recorder debug bundles include compile_ledger.json; the
  Chrome trace gains a named "compilation" track; load_profiler_result
  exposes `.compiles` / `.compile_ledger()`.
"""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu import profiler
from paddle_tpu.jit import TrainStep
from paddle_tpu.profiler import (statistic, monitor, flight_recorder,
                                 trace_export, compile_observatory)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    path = os.path.join(REPO, "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    statistic.reset_statistics()
    monitor.reset_metrics()
    flight_recorder.reset()
    compile_observatory.reset()
    yield


def _mse(out, y):
    return ((out - y) ** 2).mean()


def _make_step(width=16, seed=0, n=8):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, width), nn.ReLU(), nn.Linear(width, 4))
    o = opt.SGD(learning_rate=0.05, parameters=m.parameters())
    step = TrainStep(m, _mse, o)
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.randn(n, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randn(n, 4).astype(np.float32))
    return step, x, y


def _compile_recs(path, tag=None):
    recs = [json.loads(l) for l in open(path) if l.strip()]
    out = [r for r in recs if r.get("kind") == "compile"]
    return [r for r in out if r["tag"] == tag] if tag else out


def _retrace_events():
    return [e for e in flight_recorder.snapshot()["events"]
            if e["event"] == "retrace"]


# ------------------------------------------------- the compile ledger
def test_one_record_per_executable_signature(tmp_path, monkeypatch):
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    step, x, y = _make_step()
    float(step(x, y).item())
    float(step(x, y).item())        # warm: same signature, no record
    step.run_steps(2, x, y)
    xs = paddle.to_tensor(np.stack([x.numpy(), x.numpy()]))
    ys = paddle.to_tensor(np.stack([y.numpy(), y.numpy()]))
    float(step.accumulate(2, xs, ys).item())

    recs = _compile_recs(mfile)
    by_tag = {}
    for r in recs:
        by_tag.setdefault(r["tag"], []).append(r)
    assert set(by_tag) == {"train.step", "train.run_steps",
                           "train.accumulate"}
    assert all(len(v) == 1 for v in by_tag.values()), by_tag
    for r in recs:
        # HLO stats populated from the compiled executable itself
        assert r["instructions"] > 0
        assert r["fusion_count"] >= 0
        assert r["bytes_accessed"] > 0     # XLA cost analysis on CPU
        assert r["flops"] > 0
        assert r["peak_memory_bytes"] > 0
        assert r["lower_s"] > 0 and r["compile_s"] > 0
        assert r["cache_hit"] is False     # persistent cache off in-suite
        assert r["signature"] and isinstance(r["signature"], str)
        assert "fusion" in json.dumps(r["op_counts"]) or \
            r["fusion_count"] == 0
    # the static segment length is part of run_steps' recorded signature
    rs = by_tag["train.run_steps"][0]
    assert "n=2" in rs["args"]
    # the documented schema tool is the contract's enforcement point
    cms = _load_tool("check_metrics_schema")
    assert cms.validate_file(str(mfile)) == []
    # in-process ledger mirrors the JSONL and aggregates per tag
    agg = compile_observatory.aggregate()
    assert agg["train.step"]["signatures"] == 1
    assert agg["train.step"]["fusion_count"] == \
        by_tag["train.step"][0]["fusion_count"]


def test_inspection_paths_add_no_records(tmp_path, monkeypatch):
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    step, x, y = _make_step()
    float(step(x, y).item())
    step.compiled_text(x, y)
    step.cost_analysis(x, y)
    step.flops(x, y)
    assert len(_compile_recs(mfile, "train.step")) == 1


def test_serving_buckets_one_record_each(tmp_path, monkeypatch):
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    from paddle_tpu.inference import InferenceEngine
    paddle.seed(0)
    eng = InferenceEngine(nn.Linear(8, 4), batch_sizes=(1, 2),
                          name="obs")
    try:
        assert eng.warm(np.zeros((1, 8), np.float32)) == 2
        eng.warm(np.zeros((1, 8), np.float32))  # warm again: no records
    finally:
        eng.shutdown()
    recs = _compile_recs(mfile)
    assert sorted(r["tag"] for r in recs) == \
        ["serve.obs.batch1", "serve.obs.batch2"]
    # distinct tags per bucket: bucket laddering is NOT a retrace
    assert _retrace_events() == []


# --------------------------------------------------- retrace forensics
def test_retrace_events_name_the_changed_argument(tmp_path, monkeypatch):
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    step, x, y = _make_step(n=8)
    float(step(x, y).item())
    assert _retrace_events() == []      # first compile is not a retrace

    # shape change: both batch args shrink 8 -> 4
    rng = np.random.RandomState(1)
    x4 = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    y4 = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
    float(step(x4, y4).item())
    evs = _retrace_events()
    assert len(evs) == 1 and evs[0]["tag"] == "train.step"
    kinds = {(c["arg"], c["change"]) for c in evs[0]["changes"]}
    assert ("batch0", "shape") in kinds and ("batch1", "shape") in kinds
    shape_change = next(c for c in evs[0]["changes"]
                        if c["arg"] == "batch0")
    assert shape_change["from"] == "[8, 8]" and \
        shape_change["to"] == "[4, 8]"

    # dtype change: y flips to f16 — the diff picks the CLOSEST cached
    # signature, so the event names exactly the one changed argument
    y16 = paddle.to_tensor(rng.randn(8, 4).astype(np.float16))
    float(step(x, y16).item())
    ev = _retrace_events()[-1]
    assert ev["changes"] == [{"arg": "batch1", "change": "dtype",
                              "from": "float32", "to": "float16"}]
    assert "batch1: dtype float32 -> float16" in ev["summary"]

    # static-value change: run_steps' scanned segment length
    step.run_steps(2, x, y)
    assert len(_retrace_events()) == 2  # new tag, not a retrace
    step.run_steps(3, x, y)
    ev = _retrace_events()[-1]
    assert ev["tag"] == "train.run_steps"
    assert {"arg": "n", "change": "static",
            "from": "2", "to": "3"} in ev["changes"]

    # the events rode into the metrics JSONL as kind:"event" and the
    # whole file (compile + event records) validates
    cms = _load_tool("check_metrics_schema")
    assert cms.validate_file(str(mfile)) == []
    jl = [json.loads(l) for l in open(mfile) if l.strip()]
    assert sum(1 for r in jl if r.get("kind") == "event"
               and r.get("event") == "retrace") == 3
    assert monitor.counter("jit.retrace_events").value == 3


def test_diff_signatures_units():
    sig = compile_observatory.abstract_signature
    a = sig((np.zeros((4, 8), np.float32),), static={"n": 2})
    b = sig((np.zeros((2, 8), np.float32),), static={"n": 2})
    c = sig((np.zeros((4, 8), np.int32),), static={"n": 3})
    d = compile_observatory.diff_signatures(a, b, arg_names=("x",))
    assert d == [{"arg": "x", "change": "shape",
                  "from": "[4, 8]", "to": "[2, 8]"}]
    d = compile_observatory.diff_signatures(a, c, arg_names=("x",))
    assert {c_["change"] for c_ in d} == {"static", "dtype"}
    # identical signatures: empty diff, stable key
    assert compile_observatory.diff_signatures(a, a) == []
    assert compile_observatory.signature_key(a) == \
        compile_observatory.signature_key(sig(
            (np.zeros((4, 8), np.float32),), static={"n": 2}))
    # python scalars mirror jax weak-type semantics: a new VALUE is the
    # same signature (jit would not retrace either)
    assert compile_observatory.signature_key(sig((3,))) == \
        compile_observatory.signature_key(sig((4,)))


# ------------------------------------------- persistent-cache hit runs
_CACHE_CHILD = """
import json
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.jit import TrainStep
from paddle_tpu.framework import compile_cache

paddle.seed(0)
m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
step = TrainStep(
    m, lambda out, y: nn.functional.cross_entropy(out, y), o)
x = paddle.to_tensor(
    np.random.RandomState(0).randn(4, 16).astype(np.float32))
y = paddle.to_tensor(np.arange(4, dtype=np.int64) % 8)
float(step(x, y).item())
print(json.dumps({"entries": sorted(compile_cache.cache_entry_names())}))
"""


@pytest.mark.heavy
def test_cache_hit_records_near_zero_compile_no_new_entries(tmp_path):
    """Two processes sharing one persistent cache dir: the second's
    compile record must say cache_hit=True with near-zero compile_s and
    add NO new on-disk entries."""
    cache = tmp_path / "xla_cache"

    def run(idx):
        mfile = tmp_path / f"metrics{idx}.jsonl"
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                    "PADDLE_TPU_COMPILE_CACHE": str(cache),
                    "PADDLE_TPU_METRICS_FILE": str(mfile),
                    "PYTHONUNBUFFERED": "1"})
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "-c", _CACHE_CHILD], env=env, cwd=REPO,
            capture_output=True, text=True, timeout=240)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("{")][-1]
        return json.loads(line)["entries"], _compile_recs(
            mfile, "train.step")

    entries1, recs1 = run(1)
    assert len(recs1) == 1 and recs1[0]["cache_hit"] is False
    assert recs1[0]["cache_entries_added"] >= 1
    assert entries1, "first process wrote no cache entries"
    entries2, recs2 = run(2)
    assert len(recs2) == 1
    assert recs2[0]["cache_hit"] is True
    assert recs2[0]["cache_entries_added"] == 0
    assert entries2 == entries1          # no new on-disk entries
    # near-zero: a hit deserializes instead of compiling (the schema
    # tool enforces the same bound on every cache-hit record)
    assert recs2[0]["compile_s"] < recs1[0]["compile_s"]
    cms = _load_tool("check_metrics_schema")
    assert recs2[0]["compile_s"] <= cms.CACHE_HIT_COMPILE_S_MAX


# ------------------------------------------------------ ratchet gates
@pytest.mark.heavy
def test_gates_green_on_baseline_red_on_regression(tmp_path):
    """The canonical workload's ledger passes both gates against the
    checked-in BASELINE_HLO.json; an injected compile-time / fusion /
    bytes regression fails each gate nonzero, naming the executable."""
    gc = _load_tool("_gate_common")
    ledger = tmp_path / "ledger.jsonl"
    gc.run_workload(str(ledger))

    def gate(tool, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", tool)]
            + list(args), capture_output=True, text=True, timeout=120)

    for tool in ("check_compile_budget.py", "check_fusion.py"):
        out = gate(tool, "--ledger", str(ledger), "--require-all")
        assert out.returncode == 0, f"{tool}:\n{out.stdout}{out.stderr}"
        assert "OK:" in out.stdout

    # the ledger itself is schema-clean
    cms = _load_tool("check_metrics_schema")
    assert cms.validate_file(str(ledger)) == []

    # inject a regression into train.step only
    bad = tmp_path / "regressed.jsonl"
    with open(ledger) as f, open(bad, "w") as g:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") == "compile" and \
                    rec.get("tag") == "train.step":
                rec["compile_s"] *= 100
                rec["fusion_count"] += 50
                rec["bytes_accessed"] *= 10
            g.write(json.dumps(rec) + "\n")
    out = gate("check_compile_budget.py", "--ledger", str(bad),
               "--require-all")
    assert out.returncode == 1
    assert "train.step" in out.stdout and "exceeds budget" in out.stdout
    out = gate("check_fusion.py", "--ledger", str(bad), "--require-all")
    assert out.returncode == 1
    assert "train.step: fusion_count" in out.stdout
    assert "bytes_accessed" in out.stdout
    # the regression names ONLY the regressed executable
    assert "train.accumulate: fusion_count" not in out.stdout


def test_gate_missing_executable_fails_require_all(tmp_path):
    """A baseline tag absent from a canonical ledger (renamed
    executable) must fail loudly under --require-all."""
    cb = _load_tool("check_compile_budget")
    gc = _load_tool("_gate_common")
    baseline = gc.load_baseline(os.path.join(REPO, "BASELINE_HLO.json"))
    violations, _, _ = cb.compare(baseline, {}, 2.5, 2.0,
                                  require_all=True)
    assert violations and "not in the ledger" in violations[0]
    # without --require-all a partial ledger only notes it
    violations, notes, _ = cb.compare(baseline, {}, 2.5, 2.0,
                                      require_all=False)
    assert not violations and notes


# ------------------------------------------- downstream observability
def test_debug_bundle_includes_compile_ledger(tmp_path):
    step, x, y = _make_step()
    float(step(x, y).item())
    d = flight_recorder.dump("manual", base_dir=str(tmp_path))
    assert d is not None
    payload = json.load(open(os.path.join(d, "compile_ledger.json")))
    tags = [r["tag"] for r in payload["records"]]
    assert "train.step" in tags
    assert payload["by_tag"]["train.step"]["signatures"] == 1
    manifest = json.load(open(os.path.join(d, "MANIFEST.json")))
    assert manifest["compile_records"] == len(payload["records"])


def test_trace_export_compilation_track(tmp_path):
    step, x, y = _make_step()
    float(step(x, y).item())
    events = trace_export.chrome_trace_events()
    comp = [e for e in events if e.get("cat") == "compile"]
    names = {e["name"] for e in comp}
    assert "lower train.step" in names and "compile train.step" in names
    assert all(e["tid"] == trace_export.COMPILE_TID for e in comp)
    assert all(e["ph"] == "X" and e["dur"] > 0 for e in comp)
    sl = next(e for e in comp if e["name"] == "compile train.step")
    assert sl["args"]["tag"] == "train.step"
    assert sl["args"]["cache_hit"] is False
    # the named track rides the metadata
    assert any(e.get("ph") == "M" and e.get("name") == "thread_name"
               and e["tid"] == trace_export.COMPILE_TID
               and e["args"]["name"] == "compilation" for e in events)
    # and the whole trace still passes the lint
    path = trace_export.write_chrome_trace(str(tmp_path / "t.json"))
    cms = _load_tool("check_metrics_schema")
    assert cms.validate_file(path) == []


def test_load_profiler_result_exposes_compiles(tmp_path, monkeypatch):
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    step, x, y = _make_step()
    float(step(x, y).item())
    float(step(x, y).item())
    result = profiler.load_profiler_result(str(mfile))
    assert len(result.steps) == 2
    assert len(result.compiles) == 1
    led = result.compile_ledger()
    assert led["train.step"]["signatures"] == 1
    assert led["train.step"]["fusion_count"] >= 0
    assert "1 compile records" in result.summary()
    # host_stats.json roundtrip carries the ledger too
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    prof.stop()
    path = prof.export_host_stats(str(tmp_path / "host_stats.json"))
    back = profiler.load_profiler_result(path)
    assert back.compile_ledger()["train.step"]["signatures"] == 1


def test_compile_schema_rejects_bad_records():
    cms = _load_tool("check_metrics_schema")
    good = {"ts": 1.0, "rank": 0, "kind": "compile", "tag": "t",
            "signature": "abc", "lower_s": 0.1, "compile_s": 0.2,
            "cache_hit": False, "instructions": 10, "fusion_count": 2,
            "bytes_accessed": 100.0, "flops": 5.0,
            "peak_memory_bytes": 64.0}
    assert cms.validate_line(json.dumps(good)) == []
    bad = dict(good, compile_s=-1.0)
    assert any("compile_s" in e for e in
               cms.validate_line(json.dumps(bad)))
    bad = dict(good, cache_hit=True,
               compile_s=cms.CACHE_HIT_COMPILE_S_MAX + 1)
    assert any("cache_hit" in e for e in
               cms.validate_line(json.dumps(bad)))
    bad = dict(good)
    del bad["fusion_count"]
    assert any("fusion_count" in e for e in
               cms.validate_line(json.dumps(bad)))
    bad = dict(good, op_counts={"fusion": -1})
    assert any("op_counts" in e for e in
               cms.validate_line(json.dumps(bad)))
    bad = dict(good, tag="")
    assert any("tag" in e for e in cms.validate_line(json.dumps(bad)))
