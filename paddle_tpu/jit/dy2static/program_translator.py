"""Function-level conversion driver.

Parity: python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py:1
(ProgramTranslator + StaticFunction conversion caching). TPU-native
difference: conversion produces an ordinary Python function whose control
flow dispatches through convert_operators (lowering onto jax.lax under
trace); there is no Program/Block IR — XLA is the graph program.

Fallback contract: any function that cannot be converted (unsupported
construct, source unavailable, exotic closure) is returned UNCHANGED, which
preserves round-3 behavior: tracing works for everything except
tensor-dependent Python control flow.
"""
import ast
import functools
import inspect
import textwrap
import types
import warnings

from . import convert_operators as _ops
from .transformers import apply_transforms, UnsupportedConversion, JST

__all__ = ["convert_to_static", "conversion_enabled", "ProgramTranslator",
           "unwrap_converted"]

_cache = {}  # code object -> converted function (closure-free fns only)
_code_cache = {}  # code object -> (compiled module code, fn name) for
# closure-bearing functions: the expensive getsource+parse+transform runs
# once; per-call work is just exec with the current closure values
_fail_cache = set()  # code objects whose conversion failed: don't retry


def conversion_enabled():
    """Conversion is governed by the SAME singleton switch as
    jit-compilation (paddle.jit.ProgramTranslator, jit/debug.py) — one
    source of truth, matching the reference where ProgramTranslator.enable
    gates both."""
    from ..debug import ProgramTranslator as _PT
    return bool(getattr(_PT, "enable_to_static", True))


# re-export the canonical singleton for parity imports from dy2static
from ..debug import ProgramTranslator  # noqa: E402


def enable_to_static(flag=True):
    ProgramTranslator.enable_to_static = bool(flag)


def unwrap_converted(fn):
    return getattr(fn, "__paddle_tpu_original__", fn)


def _should_skip(tree):
    """Constructs that make re-exec unsafe: zero-arg super() needs the
    __class__ cell; locals()/globals()/eval/exec see a different frame."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id in (
                "super", "locals", "globals", "eval", "exec", "vars"):
            return node.id
    return None


def convert_to_static(fn):
    """Return a control-flow-converted version of `fn` (cached), or `fn`
    itself when conversion is not possible/needed."""
    if not conversion_enabled():
        return fn
    if getattr(fn, "__paddle_tpu_converted__", False):
        return fn
    if isinstance(fn, types.MethodType):
        conv = convert_to_static(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)

    code = getattr(fn, "__code__", None)
    if code is None:
        return fn
    if code in _fail_cache:
        return fn
    cacheable = fn.__closure__ is None
    if cacheable and code in _cache:
        cached = _cache[code]
        return cached if cached is not None else fn

    try:
        converted = _convert(fn)
    except (UnsupportedConversion, OSError, TypeError, SyntaxError,
            IndentationError) as e:
        # every fallback is LOUD (reference parity: dygraph_to_static
        # warns before running unconverted; round-4 verdict found the
        # silent path dying later with a raw TracerArrayConversionError
        # nowhere near user code)
        if isinstance(e, OSError):
            reason = "source unavailable (defined in a REPL/exec?)"
        elif isinstance(e, UnsupportedConversion):
            reason = str(e)
        else:
            reason = f"{type(e).__name__}: {e}"
        warnings.warn(
            f"paddle.jit.to_static: could not convert "
            f"{getattr(fn, '__qualname__', fn)}: {reason}; running "
            f"unconverted (tensor-dependent Python control flow will "
            f"fail under the trace)", stacklevel=2)
        converted = None
        _fail_cache.add(code)
    if cacheable:
        _cache[code] = converted
    return converted if converted is not None else fn


def _convert(fn):
    cached = _code_cache.get(fn.__code__)
    if cached is None:
        lines, first_lineno = inspect.getsourcelines(fn)
        src = textwrap.dedent("".join(lines))
        tree = ast.parse(src)
        fn_node = tree.body[0]
        if not isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        skip = _should_skip(fn_node)
        if skip is not None:
            raise UnsupportedConversion(f"use of `{skip}`")
        fn_node.decorator_list = []

        apply_transforms(fn_node)

        # Error source-mapping (reference: dygraph_to_static/error.py):
        # the transforms copy_location from the user's nodes, so shifting
        # back to the absolute line numbers and compiling against the
        # REAL source file makes any exception inside converted code
        # produce a traceback pointing at the user's own file and line —
        # no post-hoc frame rewriting needed.
        filename = inspect.getsourcefile(fn) or \
            f"<dy2static {getattr(fn, '__qualname__', fn.__name__)}>"
        ast.increment_lineno(fn_node, first_lineno - 1)
        compiled = compile(ast.Module(body=[fn_node], type_ignores=[]),
                           filename, "exec")
        cached = (compiled, fn_node.name)
        _code_cache[fn.__code__] = cached
    compiled, fname = cached

    ns = dict(fn.__globals__)
    ns[JST] = _ops
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                ns[name] = cell.cell_contents
            except ValueError:  # empty cell (e.g. recursive def)
                ns[name] = fn
    exec(compiled, ns)
    new_fn = ns[fname]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    functools.update_wrapper(new_fn, fn,
                             assigned=("__module__", "__name__",
                                       "__qualname__", "__doc__"),
                             updated=())
    new_fn.__paddle_tpu_converted__ = True
    new_fn.__paddle_tpu_original__ = fn
    return new_fn
