"""Fused multi-tensor update epilogue (ops/pallas/fused_update.py).

The contract under test: TrainStep/HybridTrainStep with the fused
epilogue (dtype-bucketed flat buffers, two Pallas passes, interpret mode
on CPU) are NUMERICALLY EQUAL to the per-leaf tree path — bit-for-bit
where only elementwise math is involved (clip off), within
reduction-order ulps where the global norm enters (clip on) — across
Adam/AdamW/Momentum/SGD, bf16 master weights, found_inf-skip semantics,
tensor lr, and the accumulate/run_steps program flavors. Plus: the
escape hatch (PADDLE_TPU_FUSED_UPDATE=0) keeps the tree path alive,
unsupported configs fall back silently, warm-pipeline coverage adds
zero executables, and the step record carries the epilogue cost split.
"""
import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.amp import GradScaler
from paddle_tpu.jit import TrainStep
from paddle_tpu.nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                                ClipGradByValue)


def _loss_fn(out, y):
    return nn.functional.cross_entropy(out, y)


def _model(seed=0, bf16=False):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    if bf16:
        m.bfloat16()
    return m


def _batch(bf16=False):
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    xt = paddle.to_tensor(x)
    if bf16:
        xt = xt.astype("bfloat16")
    return xt, paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))


def _pair(opt_factory, seed=0, bf16=False, scaler=None, **step_kw):
    """(fused_step, tree_step) over identically-seeded models."""
    steps = []
    for fused in (True, False):
        m = _model(seed, bf16)
        o = opt_factory(m)
        sc = None
        if scaler is not None:
            sc = GradScaler(**scaler)
        steps.append(TrainStep(m, _loss_fn, o, scaler=sc,
                               fused_update=fused, **step_kw))
    assert steps[0]._fused is not None, "fused path did not engage"
    assert steps[1]._fused is None
    return steps


def _assert_state_equal(a, b, exact=True, rtol=2e-6, atol=1e-7):
    """params + opt_state of two TrainSteps (tree VIEWS on both)."""
    pa, pb = a.params, b.params
    assert set(pa) == set(pb)
    for k in pa:
        x, y = np.asarray(pa[k], np.float32), np.asarray(pb[k],
                                                         np.float32)
        if exact:
            np.testing.assert_array_equal(x, y, err_msg=f"param {k}")
        else:
            np.testing.assert_allclose(x, y, rtol=rtol, atol=atol,
                                       err_msg=f"param {k}")
    sa, sb = a.opt_state, b.opt_state
    assert jax.tree.structure(sa) == jax.tree.structure(sb)
    for la, lb in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
        x, y = np.asarray(la, np.float32), np.asarray(lb, np.float32)
        if exact:
            np.testing.assert_array_equal(x, y)
        else:
            np.testing.assert_allclose(x, y, rtol=rtol, atol=atol)


# ------------------------------------------------------- path selection
def test_fused_on_by_default_and_escape_hatch(monkeypatch):
    m = _model()
    st = TrainStep(m, _loss_fn,
                   opt.AdamW(learning_rate=1e-3,
                             parameters=m.parameters()))
    assert st._fused is not None
    monkeypatch.setenv("PADDLE_TPU_FUSED_UPDATE", "0")
    st2 = TrainStep(m, _loss_fn,
                    opt.AdamW(learning_rate=1e-3,
                              parameters=m.parameters()))
    assert st2._fused is None  # escape hatch keeps the tree path alive


@pytest.mark.parametrize("make_opt", [
    lambda m: opt.LarsMomentum(learning_rate=1e-3,
                               parameters=m.parameters()),
    lambda m: opt.RMSProp(learning_rate=1e-3,
                          parameters=m.parameters()),
    lambda m: opt.AdamW(learning_rate=1e-3, parameters=m.parameters(),
                        grad_clip=ClipGradByNorm(1.0)),
])
def test_unsupported_configs_fall_back_to_tree(make_opt):
    m = _model()
    st = TrainStep(m, _loss_fn, make_opt(m))
    assert st._fused is None
    x, y = _batch()
    assert np.isfinite(float(st(x, y).item()))


def test_stochastic_rounding_falls_back():
    m = _model()
    o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
    o._stochastic_rounding = True
    assert TrainStep(m, _loss_fn, o)._fused is None


# -------------------------------------------------- numerical equality
@pytest.mark.parametrize("make_opt", [
    lambda m: opt.AdamW(learning_rate=1e-3, parameters=m.parameters()),
    lambda m: opt.Adam(learning_rate=1e-3, parameters=m.parameters()),
    lambda m: opt.Momentum(learning_rate=1e-2, momentum=0.9,
                           use_nesterov=True,
                           parameters=m.parameters()),
    lambda m: opt.SGD(learning_rate=1e-2, parameters=m.parameters()),
])
def test_fused_equals_tree_bitwise_no_clip(make_opt):
    fused, tree = _pair(make_opt)
    x, y = _batch()
    for _ in range(4):
        lf = float(fused(x, y).item())
        lt = float(tree(x, y).item())
        assert lf == lt
    _assert_state_equal(fused, tree, exact=True)


def test_fused_equals_tree_with_global_clip_and_scaler():
    fused, tree = _pair(
        lambda m: opt.AdamW(learning_rate=1e-3,
                            parameters=m.parameters(),
                            grad_clip=ClipGradByGlobalNorm(0.25)),
        scaler={"init_loss_scaling": 2.0 ** 10})
    x, y = _batch()
    for _ in range(4):
        lf, lt = float(fused(x, y).item()), float(tree(x, y).item())
        assert lf == pytest.approx(lt, rel=1e-6)
    # clip factor comes from the one shared norm: reduction order may
    # differ by ulps, everything downstream stays within float32 noise
    _assert_state_equal(fused, tree, exact=False)
    assert float(fused.scaler_state["scale"]) == \
        float(tree.scaler_state["scale"])


def test_fused_equals_tree_clip_by_value():
    fused, tree = _pair(
        lambda m: opt.Adam(learning_rate=1e-3,
                           parameters=m.parameters(),
                           grad_clip=ClipGradByValue(0.01)))
    x, y = _batch()
    for _ in range(3):
        assert float(fused(x, y).item()) == float(tree(x, y).item())
    _assert_state_equal(fused, tree, exact=True)


def test_fused_bf16_master_weights_bitwise():
    fused, tree = _pair(
        lambda m: opt.AdamW(learning_rate=0.05,
                            parameters=m.parameters(),
                            multi_precision=True),
        bf16=True)
    x, y = _batch(bf16=True)
    for _ in range(5):
        assert float(fused(x, y).item()) == float(tree(x, y).item())
    # masters (f32) and the bf16 shadow params must agree BITWISE: the
    # downcast is the numerically sharpest edge of the kernel
    _assert_state_equal(fused, tree, exact=True)
    leaf = fused.opt_state["0.weight"]
    assert isinstance(leaf, dict) and "master" in leaf
    assert leaf["master"].dtype == jnp.float32
    assert fused.params["0.weight"].dtype == jnp.bfloat16


def test_found_inf_skips_update_and_backs_off_scale():
    fused, tree = _pair(
        lambda m: opt.AdamW(learning_rate=1e-3,
                            parameters=m.parameters()),
        scaler={"init_loss_scaling": 2.0 ** 15,
                "decr_every_n_nan_or_inf": 1})
    x, y = _batch()
    bad = paddle.to_tensor(np.full((4, 8), np.inf, np.float32))
    for st in (fused, tree):
        before = np.asarray(st.params["0.weight"]).copy()
        m_before = np.asarray(jax.tree.leaves(st.opt_state)[0]).copy()
        st(bad, y)
        np.testing.assert_array_equal(
            before, np.asarray(st.params["0.weight"]))
        np.testing.assert_array_equal(
            m_before, np.asarray(jax.tree.leaves(st.opt_state)[0]))
        assert float(st.scaler_state["scale"]) == 2.0 ** 14
    # both recover identically on a good batch
    assert float(fused(x, y).item()) == float(tree(x, y).item())
    _assert_state_equal(fused, tree, exact=True)


def test_nan_without_scaler_still_updates_like_tree():
    """No GradScaler -> no found_inf skip: a NaN batch must poison the
    params on BOTH paths (the fused kernel must not invent a skip)."""
    fused, tree = _pair(
        lambda m: opt.SGD(learning_rate=1e-2,
                          parameters=m.parameters()))
    y = _batch()[1]
    bad = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    bad[0, 0] = np.nan
    bad_t = paddle.to_tensor(bad)
    fused(bad_t, y), tree(bad_t, y)
    wf = np.asarray(fused.params["0.weight"])
    wt = np.asarray(tree.params["0.weight"])
    assert np.isnan(wf).any() and np.isnan(wt).any()
    np.testing.assert_array_equal(np.isnan(wf), np.isnan(wt))


def test_tensor_lr_schedule_no_retrace_and_equal():
    """lr is a traced argument: changing it between steps must not
    recompile, and the fused kernels must consume the live value."""
    fused, tree = _pair(
        lambda m: opt.AdamW(learning_rate=1e-3,
                            parameters=m.parameters()))
    x, y = _batch()
    for lr in (1e-3, 5e-4, 2e-3):
        fused.optimizer.set_lr(lr)
        tree.optimizer.set_lr(lr)
        assert float(fused(x, y).item()) == float(tree(x, y).item())
    assert fused.retraces == 1  # lr rides as data, not as a signature
    _assert_state_equal(fused, tree, exact=True)


def test_need_clip_mask_respected_on_both_paths():
    """A Parameter with need_clip=False stays out of the global norm
    AND out of the scaling — identically on fused and tree paths."""
    def make(fused):
        m = _model(3)
        m[2].weight.need_clip = False
        o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters(),
                      grad_clip=ClipGradByGlobalNorm(0.05))
        return TrainStep(m, _loss_fn, o, fused_update=fused)
    fused, tree = make(True), make(False)
    x, y = _batch()
    for _ in range(3):
        assert float(fused(x, y).item()) == \
            pytest.approx(float(tree(x, y).item()), rel=1e-6)
    _assert_state_equal(fused, tree, exact=False)
    # and the mask actually matters: an all-clip run diverges
    allclip = _pair(lambda m: opt.AdamW(
        learning_rate=1e-2, parameters=m.parameters(),
        grad_clip=ClipGradByGlobalNorm(0.05)), seed=3)[0]
    allclip(x, y)
    w_masked = np.asarray(fused.params["2.weight"], np.float32)
    w_all = np.asarray(allclip.params["2.weight"], np.float32)
    assert not np.allclose(w_masked, w_all)


def test_accumulate_path_equality():
    fused, tree = _pair(
        lambda m: opt.AdamW(learning_rate=1e-3,
                            parameters=m.parameters(),
                            grad_clip=ClipGradByGlobalNorm(0.5)),
        scaler={"init_loss_scaling": 2.0 ** 8})
    x, y = _batch()
    k = 3
    xs = paddle.to_tensor(np.stack([np.asarray(x.value)] * k))
    ys = paddle.to_tensor(np.stack([np.asarray(y.value)] * k))
    lf = float(fused.accumulate(k, xs, ys).item())
    lt = float(tree.accumulate(k, xs, ys).item())
    assert lf == pytest.approx(lt, rel=1e-6)
    _assert_state_equal(fused, tree, exact=False)


def test_run_steps_path_equality():
    fused, tree = _pair(
        lambda m: opt.Adam(learning_rate=1e-3,
                           parameters=m.parameters()))
    x, y = _batch()
    lf = fused.run_steps(3, x, y).numpy()
    lt = tree.run_steps(3, x, y).numpy()
    np.testing.assert_array_equal(lf, lt)
    _assert_state_equal(fused, tree, exact=True)


def test_health_vector_equality_and_shared_norm():
    """monitor_health on both paths: same health scalars (the fused
    kernels produce param/update sums as pass-2 side outputs)."""
    def make(fused):
        m = _model(1)
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters(),
                      grad_clip=ClipGradByGlobalNorm(1.0))
        return TrainStep(m, _loss_fn, o, monitor_health=True,
                         fused_update=fused)
    fused, tree = make(True), make(False)
    x, y = _batch()
    for _ in range(3):
        float(fused(x, y).item()), float(tree(x, y).item())
    hf, ht = fused.flush_health(), tree.flush_health()
    for k in ("loss", "grad_norm", "param_norm", "update_ratio",
              "found_inf"):
        assert hf[k] == pytest.approx(ht[k], rel=1e-5, abs=1e-7), k


def test_checkpoint_roundtrip_restores_flat_stores(tmp_path):
    """distributed.checkpoint.load_train_state must restore through the
    layout-aware setter: params/opt_state are read-only VIEWS, the
    donated truth on the fused path is the flat stores."""
    from paddle_tpu.distributed.checkpoint import (save_train_state,
                                                   load_train_state)
    x, y = _batch()
    for fused in (True, False):
        src = TrainStep(_model(5), _loss_fn,
                        opt.AdamW(learning_rate=1e-2), fused_update=fused)
        for _ in range(2):
            float(src(x, y).item())
        path = tmp_path / f"ckpt_{fused}"
        save_train_state(src, str(path))
        dst = TrainStep(_model(6), _loss_fn,
                        opt.AdamW(learning_rate=1e-2), fused_update=fused)
        float(dst(x, y).item())  # diverge before restore
        load_train_state(dst, str(path))
        assert dst._step_i == src._step_i
        for k in src.params:
            np.testing.assert_array_equal(np.asarray(src.params[k]),
                                          np.asarray(dst.params[k]))
        for la, lb in zip(jax.tree.leaves(src.opt_state),
                          jax.tree.leaves(dst.opt_state)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        # and the restored state actually trains from where src left off
        assert float(src(x, y).item()) == float(dst(x, y).item())


def test_nan_in_need_clip_masked_leaf_trips_health_found_inf():
    """A need_clip=False leaf stays out of the shared norm, but a
    non-finite gradient there must still trip the health observatory's
    found_inf — on both epilogue paths."""
    def make(fused):
        paddle.seed(9)
        m = nn.Linear(4, 1, bias_attr=False)
        m.weight.need_clip = False
        o = opt.SGD(learning_rate=1e-2, parameters=m.parameters(),
                    grad_clip=ClipGradByGlobalNorm(1.0))
        return TrainStep(m, lambda out, t: nn.functional.mse_loss(out, t),
                         o, monitor_health=True, fused_update=fused)
    bad = np.ones((2, 4), np.float32)
    bad[0, 0] = np.nan
    xb = paddle.to_tensor(bad)
    yb = paddle.to_tensor(np.zeros((2, 1), np.float32))
    for fused in (True, False):
        st = make(fused)
        st(xb, yb)
        h = st.flush_health()
        assert h["found_inf"] == 1.0, (fused, h)


def test_pallas_interpret_mode_matches_direct():
    """The Pallas kernel plumbing (grid, BlockSpecs, scalar prefetch,
    chunk->leaf offset table) must compute exactly what the direct
    off-TPU path computes — this is what validates the TPU kernels from
    tier-1."""
    from paddle_tpu.ops.pallas.fused_update import (BucketLayout,
                                                    FusedEpilogue)
    rng = np.random.RandomState(3)
    params = {"h.0.w": jnp.asarray(rng.randn(33, 7), jnp.float32),
              "h.1.w": jnp.asarray(rng.randn(33, 7), jnp.float32),
              "b": jnp.asarray(rng.randn(130), jnp.float32)}
    grads = {k: jnp.asarray(rng.randn(*v.shape) * 0.1, v.dtype)
             for k, v in params.items()}
    o = opt.AdamW(learning_rate=0.01)
    lay = BucketLayout([(k, v.shape, v.dtype) for k, v in params.items()],
                       chunk=128)
    scaler = GradScaler(init_loss_scaling=2.0 ** 6)
    clip = ClipGradByGlobalNorm(0.5)
    outs = []
    for interpret in (False, True):
        epi = FusedEpilogue(lay, o.fused_spec(), interpret=interpret)
        assert epi.mode == ("interpret" if interpret else "direct")
        ps, osd = epi.init_stores(params, False)
        gs = lay.pack(grads)
        sstate = scaler.init_jit_state()
        outs.append(jax.jit(
            lambda g, p, s, sc: epi.finish(
                g, p, s, 0.01, 3.0, scaler=scaler, scaler_state=sc,
                clip=clip, with_stats=True))(gs, ps, osd, sstate))
    (p_a, o_a, s_a, aux_a), (p_b, o_b, s_b, aux_b) = outs
    for la, lb in zip(jax.tree.leaves((p_a, o_a, s_a)),
                      jax.tree.leaves((p_b, o_b, s_b))):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert float(aux_a["grad_norm"]) == \
        pytest.approx(float(aux_b["grad_norm"]), rel=1e-6)


# ------------------------------------------ warm pipeline / telemetry
def test_warm_adds_zero_executables_with_fused():
    from paddle_tpu.profiler import compile_observatory as cobs
    from paddle_tpu.jit import warm as jwarm
    fused, _ = _pair(lambda m: opt.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters()))
    x, y = _batch()
    jwarm.join([fused.warm(x, y)], record=False)
    warmed = cobs.ledger_signatures()
    float(fused(x, y).item())
    float(fused(x, y).item())
    assert cobs.ledger_signatures() == warmed, \
        "steady state compiled beyond the warmed set"
    assert fused.retraces == 1


def test_step_record_carries_epilogue_split(tmp_path, monkeypatch):
    mfile = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
    fused, _ = _pair(lambda m: opt.AdamW(learning_rate=1e-3,
                                         parameters=m.parameters()))
    x, y = _batch()
    for _ in range(3):
        float(fused(x, y).item())
    recs = [json.loads(l) for l in open(mfile)]
    steps = [r for r in recs if r.get("kind") == "step"]
    assert steps and all("epilogue_bytes" in r for r in steps)
    assert all(r["epilogue_bytes"] == fused._epilogue_bytes
               for r in steps)
    assert all(0.0 <= r["epilogue_share"] <= 1.0 for r in steps)
    import importlib.util as ilu
    spec = ilu.spec_from_file_location(
        "cms", os.path.join(os.path.dirname(__file__), "..", "tools",
                            "check_metrics_schema.py"))
    cms = ilu.module_from_spec(spec)
    spec.loader.exec_module(cms)
    assert cms.validate_file(str(mfile)) == []


def test_sync_to_model_roundtrip():
    fused, tree = _pair(lambda m: opt.AdamW(learning_rate=1e-2,
                                            parameters=m.parameters()))
    x, y = _batch()
    float(fused(x, y).item()), float(tree(x, y).item())
    fused.sync_to_model()
    tree.sync_to_model()
    np.testing.assert_array_equal(
        np.asarray(fused.model[0].weight.value),
        np.asarray(tree.model[0].weight.value))


# --------------------------------------------------- hybrid (per-shard)
def _hybrid_pair(mesh, make_opt, scaler=None, **kw):
    from paddle_tpu.distributed.fleet.hybrid_train import HybridTrainStep
    steps = []
    for fused in (True, False):
        m = _model(7)
        o = make_opt(m)
        sc = GradScaler(**scaler) if scaler else None
        steps.append(HybridTrainStep(m, _loss_fn, o, mesh, scaler=sc,
                                     fused_update=fused, **kw))
    assert steps[0]._fused is not None and steps[1]._fused is None
    return steps


def _hybrid_batch():
    x = paddle.to_tensor(
        np.random.RandomState(1).randn(8, 8).astype(np.float32))
    y = paddle.to_tensor(np.arange(8, dtype=np.int64) % 4)
    return x, y


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_hybrid_fused_equals_tree(stage):
    from paddle_tpu.distributed.env import build_mesh
    mesh = build_mesh(dp=2, mp=2, sharding=2)
    fused, tree = _hybrid_pair(
        mesh,
        lambda m: opt.AdamW(learning_rate=1e-3,
                            parameters=m.parameters(),
                            grad_clip=ClipGradByGlobalNorm(0.5)),
        scaler={"init_loss_scaling": 2.0 ** 8},
        sharding_stage=stage)
    x, y = _hybrid_batch()
    for _ in range(3):
        lf, lt = float(fused(x, y).item()), float(tree(x, y).item())
        assert lf == pytest.approx(lt, rel=1e-5)
    for k in fused.params:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(fused.params[k]), np.float32),
            np.asarray(jax.device_get(tree.params[k]), np.float32),
            rtol=3e-6, atol=1e-7, err_msg=f"param {k} (stage {stage})")
    assert float(fused.scaler_state["scale"]) == \
        float(tree.scaler_state["scale"])


def test_hybrid_fused_health_and_psum_norm():
    """The ONE psum'd global norm must equal the tree-path norm even
    with leaves replicated over dp (norm_weight de-duplication)."""
    from paddle_tpu.distributed.env import build_mesh
    mesh = build_mesh(dp=4, mp=2)
    fused, tree = _hybrid_pair(
        mesh,
        lambda m: opt.AdamW(learning_rate=1e-3,
                            parameters=m.parameters()),
        monitor_health=True)
    x, y = _hybrid_batch()
    for _ in range(2):
        float(fused(x, y).item()), float(tree(x, y).item())
    hf, ht = fused.flush_health(), tree.flush_health()
    for k in ("loss", "grad_norm", "param_norm", "update_ratio"):
        assert hf[k] == pytest.approx(ht[k], rel=1e-5), k
