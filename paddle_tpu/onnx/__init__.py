"""Parity: python/paddle/onnx/__init__.py.

ONNX export is explicitly out of scope for the TPU build (SURVEY.md §3):
the deployment format here is StableHLO via ``paddle.jit.save`` /
``jax.export``, which XLA consumes directly. ``export`` is kept as a
documented stub so code probing the API gets a clear, actionable error.
"""
__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Reference: python/paddle/onnx/export.py:21 (paddle2onnx bridge)."""
    raise NotImplementedError(
        "ONNX export is not supported by the TPU build; use "
        "paddle.jit.save(layer, path) to produce a portable StableHLO "
        "artifact and paddle.inference to run it.")
