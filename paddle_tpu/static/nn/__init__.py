"""paddle.static.nn — graph-building layer functions.

Parity: python/paddle/static/nn/__init__.py (fc, conv*, norms, sequence_*
ops, control flow). TPU-native design: these build eagerly-traced values in
a ``static.Program`` rather than appending OpDescs; sequence_* ops operate
on padded dense [batch, time, ...] tensors (the TPU layout) instead of
LoDTensors — an explicit ``seq_len`` / mask argument replaces LoD levels.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op
from ...framework.dtype import convert_dtype

__all__ = [  # noqa
    'fc', 'batch_norm', 'embedding', 'bilinear_tensor_product', 'case',
    'cond', 'conv2d', 'conv2d_transpose', 'conv3d', 'conv3d_transpose',
    'crf_decoding', 'data_norm', 'deform_conv2d', 'group_norm',
    'instance_norm', 'layer_norm', 'multi_box_head', 'nce', 'prelu',
    'py_func', 'row_conv', 'spectral_norm', 'switch_case', 'while_loop',
    'sparse_embedding', 'sequence_conv', 'sequence_softmax',
    'sequence_pool', 'sequence_concat', 'sequence_first_step',
    'sequence_last_step', 'sequence_slice', 'sequence_expand',
    'sequence_expand_as', 'sequence_pad', 'sequence_unpad',
    'sequence_reshape', 'sequence_scatter', 'sequence_enumerate',
    'sequence_reverse',
]


def _F():
    from ... import nn
    return nn.functional


# ---------------------------------------------------------------- layers

def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from ...nn.layer.common import Linear
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = []
    for xi in xs:
        lin = _track(Linear(
            int(np.prod(xi.shape[num_flatten_dims:])), size,
            weight_attr=weight_attr, bias_attr=bias_attr))
        # flatten from the RUNTIME shape, not the build-time one: the
        # Executor replays this op with feeds whose batch dim may differ
        # from the placeholder's build-time size
        from ...framework.core import apply_op
        nfd = num_flatten_dims
        flat = apply_op(lambda a: a.reshape(a.shape[:nfd] + (-1,)), xi)
        outs.append(lin(flat))
    out = outs[0]
    for o in outs[1:]:
        out = out + o
    if activation:
        out = getattr(_F(), activation)(out)
    return out


def _track(layer):
    """Register a static.nn layer's parameters on the active Program so
    append_backward(parameter_list=None) can find them (reference
    static/backward.py walks the program's params)."""
    from .. import default_main_program
    prog = default_main_program()
    for _, prm in layer.named_parameters():
        prog._params.append(prm)
    return layer


def _make_param(shape, dtype, attr, default_init):
    from ...nn.layer.layers import Layer
    holder = Layer()
    p = holder.create_parameter(shape, attr=attr, dtype=dtype,
                                default_initializer=default_init)
    from .. import default_main_program
    default_main_program()._params.append(p)
    return p


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype='float32'):
    from ...nn.layer.common import Embedding
    emb = _track(Embedding(size[0], size[1], padding_idx=padding_idx,
                           weight_attr=param_attr))
    return emb(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="CommonSparseTable",
                     param_attr=None, dtype='float32', slot=None):
    """Parameter-server sparse table → dense embedding on TPU (the table
    lives in HBM; XLA gathers are already sparse reads)."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    from ...nn.layer.conv import Conv2D
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = Conv2D(cin, num_filters, k, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format=data_format)
    _track(layer)
    out = layer(input)
    if act:
        out = getattr(_F(), act)(out)
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from ...nn.layer.conv import Conv2DTranspose
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = Conv2DTranspose(cin, num_filters, k, stride=stride,
                            padding=padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format)
    _track(layer)
    out = layer(input, output_size=output_size)
    if act:
        out = getattr(_F(), act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    from ...nn.layer.conv import Conv3D
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = Conv3D(cin, num_filters, k, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format=data_format)
    _track(layer)
    out = layer(input)
    if act:
        out = getattr(_F(), act)(out)
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from ...nn.layer.conv import Conv3DTranspose
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = Conv3DTranspose(cin, num_filters, k, stride=stride,
                            padding=padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format)
    _track(layer)
    out = layer(input, output_size=output_size)
    if act:
        out = getattr(_F(), act)(out)
    return out


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, weight_attr=None, bias_attr=None,
                  name=None):
    from ...vision.ops import deform_conv2d as _dc
    from ...nn.initializer import XavierNormal, Constant
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    cin = x.shape[1]
    w = _make_param([num_filters, cin // groups, k[0], k[1]], 'float32',
                    weight_attr, XavierNormal())
    b = None if bias_attr is False else \
        _make_param([num_filters], 'float32', bias_attr, Constant(0.0))
    return _dc(x, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from ...nn.layer.norm import BatchNorm2D, BatchNorm1D, BatchNorm3D
    c = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    nd = len(input.shape)
    cls = {2: BatchNorm1D, 3: BatchNorm1D, 4: BatchNorm2D, 5: BatchNorm3D}[nd]
    layer = cls(c, momentum=momentum, epsilon=epsilon,
                weight_attr=param_attr, bias_attr=bias_attr,
                data_format=data_layout if nd == 4 else 'NCL')
    if is_test or use_global_stats:
        layer.eval()
    _track(layer)
    out = layer(input)
    if act:
        out = getattr(_F(), act)(out)
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout='NCHW', in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_0=0.9999999, sync_stats=False,
              summary_decay=0.9999999, enable_scale_and_shift=False):
    """Normalize by running batch statistics (no learned affine unless
    enable_scale_and_shift). Parity: fluid/layers/nn.py data_norm."""
    mean = input.mean(axis=0, keepdim=True)
    var = ((input - mean) ** 2).mean(axis=0, keepdim=True)
    out = (input - mean) / (var + epsilon).sqrt()
    if act:
        out = getattr(_F(), act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout='NCHW', name=None):
    from ...nn.layer.norm import GroupNorm
    c = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    layer = GroupNorm(groups, c, epsilon=epsilon, weight_attr=param_attr,
                      bias_attr=bias_attr)
    _track(layer)
    out = layer(input)
    if act:
        out = getattr(_F(), act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ...nn.layer.norm import InstanceNorm2D, InstanceNorm1D, InstanceNorm3D
    nd = len(input.shape)
    cls = {3: InstanceNorm1D, 4: InstanceNorm2D, 5: InstanceNorm3D}[nd]
    layer = cls(input.shape[1], epsilon=epsilon, weight_attr=param_attr,
                bias_attr=bias_attr)
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ...nn.layer.norm import LayerNorm
    norm_shape = list(input.shape[begin_norm_axis:])
    layer = LayerNorm(norm_shape, epsilon=epsilon,
                      weight_attr=param_attr if scale else False,
                      bias_attr=bias_attr if shift else False)
    _track(layer)
    out = layer(input)
    if act:
        out = getattr(_F(), act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Power-iteration spectral normalization of a weight tensor.
    Parity: fluid/layers/nn.py spectral_norm."""
    w = weight.value if isinstance(weight, Tensor) else jnp.asarray(weight)
    shape = w.shape
    perm = [dim] + [i for i in range(len(shape)) if i != dim]
    mat = jnp.transpose(w, perm).reshape(shape[dim], -1)
    key = jax.random.PRNGKey(0)
    u = jax.random.normal(key, (shape[dim],), mat.dtype)
    v = None
    for _ in range(max(1, power_iters)):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ (mat @ v)
    return apply_op(lambda a: a / sigma, weight)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    from ...nn.initializer import Constant
    if mode == 'all':
        n = 1
    elif mode == 'channel':
        n = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    else:  # element
        n = int(np.prod(x.shape[1:]))
    alpha = _make_param([n], 'float32', param_attr, Constant(0.25))
    a = alpha.value
    if mode == 'channel':
        shape = [1, n] + [1] * (len(x.shape) - 2) if data_format == "NCHW" \
            else [1] * (len(x.shape) - 1) + [n]
        a = a.reshape(shape)
    elif mode == 'element':
        a = a.reshape((1,) + tuple(x.shape[1:]))
    return apply_op(lambda xx: jnp.where(xx >= 0, xx, a * xx), x)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from ...nn.layer.common import Bilinear
    layer = Bilinear(x.shape[-1], y.shape[-1], size, weight_attr=param_attr,
                     bias_attr=bias_attr)
    out = layer(x, y)
    if act:
        out = getattr(_F(), act)(out)
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (Deep Speech 2). Each timestep mixes the
    next `future_context_size` frames: out[t] = sum_{i=0..k} w[i]*x[t+i].
    Parity: fluid/layers/nn.py row_conv. Dense [B,T,D] layout."""
    from ...nn.initializer import Constant
    k = future_context_size + 1
    d = input.shape[-1]
    w = _make_param([k, d], 'float32', param_attr, Constant(1.0 / k))
    wv = w.value

    def _rc(x):
        pads = [(0, 0), (0, k - 1), (0, 0)]
        xp = jnp.pad(x, pads)
        out = sum(xp[:, i:i + x.shape[1], :] * wv[i] for i in range(k))
        return out
    out = apply_op(_rc, input)
    if act:
        out = getattr(_F(), act)(out)
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (sampled softmax formulation on
    TPU — the candidate gather is an XLA gather, not a sparse table op).
    Parity: fluid/layers/nn.py nce."""
    from ...nn.initializer import XavierNormal, Constant
    d = input.shape[-1]
    num_neg = num_neg_samples or 10
    w = _make_param([num_total_classes, d], 'float32', param_attr,
                    XavierNormal())
    b = _make_param([num_total_classes], 'float32', bias_attr, Constant(0.0))
    key = jax.random.PRNGKey(seed or 0)
    neg = jax.random.randint(key, (num_neg,), 0, num_total_classes)

    def _nce(x, lab):
        lab = lab.reshape(-1)
        pos_w = w.value[lab]                      # [B, D]
        pos_logit = (x * pos_w).sum(-1) + b.value[lab]
        neg_w = w.value[neg]                      # [K, D]
        neg_logit = x @ neg_w.T + b.value[neg]    # [B, K]
        pos_loss = jax.nn.softplus(-pos_logit)
        neg_loss = jax.nn.softplus(neg_logit).sum(-1)
        return (pos_loss + neg_loss).reshape(-1, 1)
    return apply_op(_nce, input, label)


def crf_decoding(input, param_attr=None, label=None, length=None):
    """Viterbi decode with a learned transition matrix.
    Parity: fluid/layers/nn.py crf_decoding → text.viterbi_decode."""
    from ...text import ViterbiDecoder
    from ...nn.initializer import Constant
    n = input.shape[-1]
    trans = _make_param([n + 2, n], 'float32', param_attr, Constant(0.0))
    dec = ViterbiDecoder(trans[2:], include_bos_eos_tag=False)
    if len(input.shape) == 2:
        input = input.unsqueeze(0)
    lens = length if length is not None else \
        Tensor(jnp.full((input.shape[0],), input.shape[1], jnp.int64))
    _, path = dec(input, lens)
    return path


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head: per-feature-map loc/conf convs + prior boxes.
    Parity: fluid/layers/detection.py multi_box_head."""
    from ...vision.ops import prior_box as _prior_box
    n_layer = len(inputs)
    if min_sizes is None:
        min_sizes, max_sizes = [], []
        step = int(np.floor((max_ratio - min_ratio) / (n_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes
    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, x in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i]
        box, var = _prior_box(
            x, image,
            min_sizes=[mins] if not isinstance(mins, list) else mins,
            max_sizes=[maxs] if maxs and not isinstance(maxs, list) else
            (maxs or []),
            aspect_ratios=ar if isinstance(ar, (list, tuple)) else [ar],
            variance=variance, flip=flip, clip=clip, offset=offset,
            steps=[steps[i], steps[i]] if steps else [0.0, 0.0])
        nbox = int(np.prod(box.shape[:-1]))
        loc = conv2d(x, nbox // (x.shape[2] * x.shape[3]) * 4, kernel_size,
                     padding=pad, stride=stride)
        conf = conv2d(x, nbox // (x.shape[2] * x.shape[3]) * num_classes,
                      kernel_size, padding=pad, stride=stride)
        locs.append(loc.transpose([0, 2, 3, 1]).reshape([loc.shape[0], -1, 4]))
        confs.append(conf.transpose([0, 2, 3, 1]).reshape(
            [conf.shape[0], -1, num_classes]))
        boxes_all.append(box.reshape([-1, 4]))
        vars_all.append(var.reshape([-1, 4]))
    from ... import concat
    return (concat(locs, 1), concat(confs, 1), concat(boxes_all, 0),
            concat(vars_all, 0))


# ----------------------------------------------------- control flow / misc
# Traced-predicate dispatch: when the predicate (or a loop var) is a jax
# Tracer — i.e. we are inside jit / to_static — these lower to
# lax.cond/lax.switch/lax.while_loop so the control flow compiles into the
# XLA program (reference converts Python control flow the same way:
# fluid/dygraph/dygraph_to_static/convert_operators.py:26,191). With
# concrete values they stay plain Python (eager parity).

def _cf_leaf(x):
    return isinstance(x, Tensor)


def _cf_arr(tree):
    """Tensor -> jnp array through nested lists/tuples/dicts."""
    import jax
    return jax.tree_util.tree_map(
        lambda t: t.value if isinstance(t, Tensor) else t, tree,
        is_leaf=_cf_leaf)


def _cf_ten(tree):
    """array -> Tensor through nested lists/tuples/dicts."""
    import jax
    return jax.tree_util.tree_map(
        lambda a: Tensor(a) if hasattr(a, "dtype") else a, tree)


def _cf_traced(x):
    import jax
    v = x.value if isinstance(x, Tensor) else x
    return isinstance(v, jax.core.Tracer)


def _cf_pred(p):
    v = p.value if isinstance(p, Tensor) else jnp.asarray(p)
    return jnp.reshape(v, ()).astype(bool)


def cond(pred, true_fn=None, false_fn=None, name=None):
    if _cf_traced(pred):
        import jax
        tf = (lambda _: _cf_arr(true_fn())) if true_fn else (lambda _: None)
        ff = (lambda _: _cf_arr(false_fn())) if false_fn else (lambda _: None)
        return _cf_ten(jax.lax.cond(_cf_pred(pred), tf, ff, None))
    if bool(pred.item() if isinstance(pred, Tensor) else pred):
        return true_fn() if true_fn else None
    return false_fn() if false_fn else None


def case(pred_fn_pairs, default=None, name=None):
    if any(_cf_traced(p) for p, _ in pred_fn_pairs):
        import jax
        preds = jnp.stack([_cf_pred(p) for p, _ in pred_fn_pairs])
        first = jnp.argmax(preds)  # index of first True
        branch = jnp.where(jnp.any(preds), first, len(pred_fn_pairs))
        fns = [fn for _, fn in pred_fn_pairs]
        fns.append(default if default is not None else pred_fn_pairs[-1][1])
        return _cf_ten(jax.lax.switch(
            branch, [lambda _, f=f: _cf_arr(f()) for f in fns], None))
    for pred, fn in pred_fn_pairs:
        if bool(pred.item() if isinstance(pred, Tensor) else pred):
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    table = dict(branch_fns) if not isinstance(branch_fns, dict) \
        else branch_fns
    if _cf_traced(branch_index):
        import jax
        keys = sorted(table)
        karr = jnp.asarray(keys)
        idx = jnp.reshape(branch_index.value if isinstance(
            branch_index, Tensor) else branch_index, ()).astype(karr.dtype)
        hit = karr == idx
        branch = jnp.where(jnp.any(hit), jnp.argmax(hit), len(keys))
        fns = [table[k] for k in keys]
        fns.append(default if default is not None else table[max(table)])
        return _cf_ten(jax.lax.switch(
            branch, [lambda _, f=f: _cf_arr(f()) for f in fns], None))
    idx = int(branch_index.item() if isinstance(branch_index, Tensor)
              else branch_index)
    if idx in table:
        return table[idx]()
    if default is not None:
        return default()
    return table[max(table)]()


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    import jax
    vals = list(loop_vars)
    leaves = jax.tree_util.tree_leaves(vals, is_leaf=_cf_leaf)
    first = cond(*vals)  # evaluated once; reused by the eager path below
    if any(_cf_traced(v) for v in leaves) or _cf_traced(first):
        def c(carry):
            return _cf_pred(cond(*_cf_ten(carry)))

        def b(carry):
            out = body(*_cf_ten(carry))
            out = list(out) if isinstance(out, (list, tuple)) else [out]
            return _cf_arr(out)

        return _cf_ten(jax.lax.while_loop(c, b, _cf_arr(vals)))
    c = first
    while bool(c.item() if isinstance(c, Tensor) else c):
        out = body(*vals)
        vals = list(out) if isinstance(out, (list, tuple)) else [out]
        c = cond(*vals)
    return vals


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    from .. import py_func as _pf
    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


# --------------------------------------------- sequence ops (dense [B,T,*])

def _dense(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Context-window conv over [B, T, D] padded sequences."""
    from ...nn.initializer import XavierNormal, Constant
    d = input.shape[-1]
    w = _make_param([filter_size * d, num_filters], 'float32', param_attr,
                    XavierNormal())
    b = None if bias_attr is False else _make_param(
        [num_filters], 'float32', bias_attr, Constant(0.0))
    start = padding_start if padding_start is not None \
        else -((filter_size - 1) // 2)

    def _sc(x):
        T = x.shape[1]
        cols = []
        for i in range(filter_size):
            off = start + i
            if off < 0:
                xp = jnp.pad(x, [(0, 0), (-off, 0), (0, 0)])[:, :T]
            else:
                xp = jnp.pad(x, [(0, 0), (0, off), (0, 0)])[:, off:off + T]
            cols.append(xp)
        col = jnp.concatenate(cols, -1)          # [B, T, k*D]
        out = col @ w.value
        if b is not None:
            out = out + b.value
        return out
    out = apply_op(_sc, input)
    if act:
        out = getattr(_F(), act)(out)
    return out


def sequence_softmax(input, use_cudnn=False, name=None):
    return apply_op(lambda x: jax.nn.softmax(x, axis=1), input)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    pt = pool_type.lower()

    def _sp(x):
        if pt == 'sum':
            return x.sum(1)
        if pt in ('average', 'avg'):
            return x.mean(1)
        if pt == 'max':
            return x.max(1)
        if pt == 'sqrt':
            return x.sum(1) / jnp.sqrt(x.shape[1])
        if pt == 'first':
            return x[:, 0]
        if pt == 'last':
            return x[:, -1]
        raise ValueError(f"unsupported pool_type {pool_type}")
    return apply_op(_sp, input)


def sequence_concat(input, name=None):
    return apply_op(lambda *xs: jnp.concatenate(xs, axis=1),
                    *input)


def sequence_first_step(input):
    return apply_op(lambda x: x[:, 0], input)


def sequence_last_step(input):
    return apply_op(lambda x: x[:, -1], input)


def sequence_slice(input, offset, length, name=None):
    off = _dense(offset).reshape(-1)
    ln = _dense(length).reshape(-1)

    def _ss(x):
        outs = [jax.lax.dynamic_slice_in_dim(x[i], int(off[i]), int(ln[i]))
                for i in range(x.shape[0])]
        return jnp.stack(outs)
    return apply_op(_ss, input)


def sequence_expand(x, y, ref_level=-1, name=None):
    reps = y.shape[1] if len(y.shape) > 1 else 1
    return apply_op(lambda a: jnp.repeat(a, reps, axis=0), x)


def sequence_expand_as(x, y, name=None):
    t = y.shape[1]
    return apply_op(
        lambda a: jnp.repeat(a[:, None, ...], t, axis=1), x)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    pv = float(_dense(pad_value).reshape(-1)[0])

    def _pad(a):
        T = a.shape[1]
        m = maxlen or T
        if m > T:
            pads = [(0, 0), (0, m - T)] + [(0, 0)] * (a.ndim - 2)
            a = jnp.pad(a, pads, constant_values=pv)
        return a[:, :m]
    out = apply_op(_pad, x)
    lens = Tensor(jnp.full((x.shape[0],), x.shape[1], jnp.int64))
    return out, lens


def sequence_unpad(x, length, name=None):
    ln = _dense(length).reshape(-1)
    m = int(ln.max()) if ln.size else x.shape[1]
    return apply_op(lambda a: a[:, :m], x)


def sequence_reshape(input, new_dim):
    return apply_op(
        lambda x: x.reshape(x.shape[0], -1, new_dim), input)


def sequence_scatter(input, index, updates, name=None):
    idx = _dense(index).reshape(-1).astype(jnp.int32)

    def _sct(x, u):
        u2 = u.reshape(-1, *x.shape[2:])
        b = jnp.repeat(jnp.arange(x.shape[0]),
                       u2.shape[0] // x.shape[0])
        return x.at[b, idx].add(u2)
    return apply_op(_sct, input, updates)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    def _en(x):
        T = x.shape[-1] if x.ndim == 2 else x.shape[1]
        x2 = x.reshape(x.shape[0], -1)
        xp = jnp.pad(x2, [(0, 0), (0, win_size - 1)],
                     constant_values=pad_value)
        wins = jnp.stack([xp[:, i:i + T] for i in range(win_size)], -1)
        return wins
    return apply_op(_en, input)


def sequence_reverse(x, name=None):
    return apply_op(lambda a: jnp.flip(a, axis=1), x)
