"""Seq2seq decoding. Parity: python/paddle/nn/decode.py
(BeamSearchDecoder + dynamic_decode over RNNCell/attention decoders)."""
import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op, no_grad
from .layers import Layer

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    """Length-normalized beam search over a cell + embedding + output fn."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        def fn(a):
            a = jnp.repeat(a[:, None], beam_size, axis=1)
            return a.reshape((-1,) + a.shape[2:])
        return apply_op(fn, x)

    def initialize(self, initial_cell_states):
        B = initial_cell_states[0].shape[0] if isinstance(
            initial_cell_states, (tuple, list)) \
            else initial_cell_states.shape[0]
        from ...tensor.creation import full
        start = full([B * self.beam_size], self.start_token, dtype="int64")
        states = jax.tree.map(
            lambda t: BeamSearchDecoder.tile_beam_merge_with_batch(
                t, self.beam_size),
            initial_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        return start, states

    def step(self, inputs, states):
        emb = self.embedding_fn(inputs) if self.embedding_fn else inputs
        out, new_states = self.cell(emb, states)
        logits = self.output_fn(out) if self.output_fn else out
        return logits, new_states


@no_grad()
def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Greedy/beam decode loop (eager; generation is latency-bound host
    orchestration — the per-step cell math still jits)."""
    tokens, states = decoder.initialize(inits)
    beam = decoder.beam_size
    BK = tokens.shape[0]
    B = BK // beam
    neg_inf = -1e9

    scores = np.zeros((B, beam), np.float32)
    scores[:, 1:] = neg_inf  # all beams start identical: keep one
    finished = np.zeros((B, beam), bool)
    outputs = []
    lengths = np.zeros((B, beam), np.int64)

    cur = tokens
    for t in range(max_step_num):
        logits, states = decoder.step(cur, states)
        logp = jax.nn.log_softmax(logits.value.astype(jnp.float32), -1)
        V = logp.shape[-1]
        logp = np.array(logp).reshape(B, beam, V)  # writable copy
        # frozen finished beams only extend with end_token (score 0)
        logp[finished] = neg_inf
        logp[finished, decoder.end_token] = 0.0
        total = scores[:, :, None] + logp
        flat = total.reshape(B, beam * V)
        top_idx = np.argpartition(-flat, beam, 1)[:, :beam]
        top_val = np.take_along_axis(flat, top_idx, 1)
        order = np.argsort(-top_val, 1)
        top_idx = np.take_along_axis(top_idx, order, 1)
        scores = np.take_along_axis(top_val, order, 1)
        parent = top_idx // V
        word = top_idx % V
        finished = np.take_along_axis(finished, parent, 1) | \
            (word == decoder.end_token)
        lengths = np.take_along_axis(lengths, parent, 1) + (~finished)
        outputs.append((word.copy(), parent.copy()))
        # reorder states along the merged batch*beam axis
        gather = (parent + np.arange(B)[:, None] * beam).reshape(-1)
        states = jax.tree.map(
            lambda s: Tensor(s.value[gather]) if isinstance(s, Tensor)
            else s, states, is_leaf=lambda s: isinstance(s, Tensor))
        cur = Tensor(jnp.asarray(word.reshape(-1), jnp.int64))
        if finished.all():
            break

    # backtrace
    T = len(outputs)
    ids = np.stack([w for w, _ in outputs])       # [T, B, beam]
    parents = np.stack([p for _, p in outputs])
    from ..functional.misc_gap import gather_tree
    seqs = gather_tree(Tensor(ids), Tensor(parents))
    out = seqs if output_time_major else Tensor(
        np.transpose(seqs.numpy(), (1, 2, 0)))
    if return_length:
        return out, Tensor(lengths)
    return out, Tensor(scores)
