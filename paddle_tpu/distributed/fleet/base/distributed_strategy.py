"""DistributedStrategy. Parity:
python/paddle/distributed/fleet/base/distributed_strategy.py (a protobuf-
backed config in the reference; a plain config object here — the strategy
fields map onto mesh axes and jit options instead of graph passes).
"""

__all__ = ["DistributedStrategy"]


class _Cfg(dict):
    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel degrees (consumed by fleet.init → Mesh axes)
        self.hybrid_configs = _Cfg({
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1, "ep_degree": 1,
        })
        # feature switches — each maps to a TPU-native mechanism
        self.amp = False                      # bf16/fp16 autocast policy
        self.amp_configs = _Cfg({"init_loss_scaling": 32768.0,
                                 "use_pure_fp16": False,
                                 "use_bf16": True,
                                 "custom_white_list": [],
                                 "custom_black_list": []})
        self.recompute = False                # jax.checkpoint on blocks
        self.recompute_configs = _Cfg({"checkpoints": []})
        self.sharding = False                 # ZeRO over 'sharding' axis
        self.sharding_configs = _Cfg({"stage": 1,
                                      "sharding_degree": 1})
        self.pipeline = False
        self.pipeline_configs = _Cfg({"accumulate_steps": 1,
                                      "micro_batch_size": 1,
                                      "schedule_mode": "1F1B"})
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Cfg({"tensor_parallel_degree": 1})
        self.gradient_merge = False
        self.gradient_merge_configs = _Cfg({"k_steps": 1, "avg": True})
        self.lamb = False
        self.lamb_configs = _Cfg({"lamb_weight_decay": 0.01})
        self.lars = False
        self.lars_configs = _Cfg({})
        self.dgc = False                      # out of scope (SURVEY §3)
        self.localsgd = False                 # K local steps, then pmean
        self.localsgd_configs = _Cfg({"k_steps": 4, "begin_step": 1})
        self.asp = False                      # out of scope (SURVEY §3)
        self.fuse_all_reduce_ops = True       # XLA fuses automatically
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.find_unused_parameters = False
        self.gradient_scale_configs = _Cfg({"scale_strategy": "avg"})
        self.a_sync = False                   # parameter-server mode: N/A
        self.a_sync_configs = _Cfg({})
        self.auto = False
        self.semi_auto = False

    def __repr__(self):
        flags = [k for k in ("amp", "recompute", "sharding", "pipeline",
                             "tensor_parallel") if getattr(self, k)]
        return (f"DistributedStrategy(hybrid={dict(self.hybrid_configs)}, "
                f"enabled={flags})")
