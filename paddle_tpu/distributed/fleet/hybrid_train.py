"""Hybrid-parallel jitted train step — the fleet execution engine.

The TPU-native replacement for the reference's HybridParallelOptimizer +
PipelineParallel + ShardingStage2 runtime classes (distributed/fleet/
meta_parallel/*): one jax.jit'ed SPMD program over the fleet mesh where

- batch is sharded over ('dp',)                       [data parallel]
- params follow per-layer PartitionSpecs over 'mp'    [tensor parallel]
- optimizer states are additionally sharded over the
  'sharding' axis (ZeRO-1/2)                          [sharding]
- blocks can be rematerialized (jax.checkpoint)       [recompute]
- gradient accumulation folds microbatches in a scan  [gradient_merge /
                                                       pipeline microbatch]

XLA inserts psum for dp grad sync (reference: reducer.cc fused allreduce),
allreduce/allgather for mp (reference: mp_allreduce), and reduce-scatter
for ZeRO — all over ICI.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.core import Tensor, no_grad, _Slot
from ...framework.random import split_key
from ...framework.jax_compat import shard_map
from ...framework import fault_injection as _fault
from ...jit.api import (functional_call, state_arrays, aot_compile,
                        count_train_use, export_step_metrics,
                        HealthMonitorMixin, CheckpointSnapshotMixin,
                        fire_step_faults, _step_arg_names,
                        epilogue_leaf_meta, device_probe_open,
                        device_probe_close)
from ...jit import warm as _warm
from ...jit.deferred import DeferredLoss
from ...profiler import statistic as _stat
from ...profiler import monitor as _monitor
from ...profiler import cost as _cost
from ...profiler import flight_recorder as _flight
from ...profiler import mem_observatory as _mobs

__all__ = ["HybridTrainStep", "default_param_rules"]


def default_param_rules(name, arr):
    """Name-based PartitionSpec rules for transformer-family models when a
    layer doesn't announce its own sharding_spec."""
    if arr.ndim == 2:
        if any(k in name for k in ("qkv_proj.weight", "fc_in.weight",
                                   "q_proj.weight", "k_proj.weight",
                                   "v_proj.weight", "linear1.weight")):
            return P(None, "mp")
        if any(k in name for k in ("out_proj.weight", "fc_out.weight",
                                   "linear2.weight")):
            return P("mp", None)
        if any(k in name for k in ("wte.weight", "embed_tokens.weight",
                                   "word_embeddings.weight")):
            return P("mp", None)
    if arr.ndim == 1 and any(k in name for k in ("qkv_proj.bias",
                                                 "fc_in.bias",
                                                 "linear1.bias")):
        return P("mp")
    return P()


def _collect_specs(model, params):
    """Layer-announced sharding_spec()s override the name rules."""
    specs = {}
    for lname, layer in model.named_sublayers(include_self=True):
        spec_fn = getattr(layer, "sharding_spec", None)
        if spec_fn is None:
            continue
        for pname, spec in spec_fn().items():
            full = f"{lname}.{pname}" if lname else pname
            specs[full] = spec
    out = {}
    for k, v in params.items():
        out[k] = specs.get(k, default_param_rules(k, v))
    return out


def _zero_spec(pspec, mesh, arr):
    """Extend a param spec with the 'sharding' axis on the first
    axis that is unsharded and divisible (ZeRO state placement)."""
    deg = mesh.shape.get("sharding", 1)
    if deg == 1:
        return pspec
    dims = list(pspec) + [None] * (arr.ndim - len(pspec))
    for i, d in enumerate(dims):
        if d is None and arr.shape[i] % deg == 0 and arr.shape[i] >= deg:
            dims[i] = "sharding"
            return P(*dims)
    return pspec


class HybridTrainStep(HealthMonitorMixin, CheckpointSnapshotMixin):
    """Build once, call per batch. See module docstring."""

    def __init__(self, model, loss_fn, optimizer, mesh, recompute=False,
                 accumulate_steps=1, donate=True, param_dtype=None,
                 sharding_stage=1, scaler=None, monitor_health=False,
                 fused_update=None):
        """sharding_stage selects the ZeRO behavior over the 'sharding'
        mesh axis (ref sharding/sharding_stage2.py:43, sharding_stage3.py:51):
          1 — optimizer state sharded (grads allreduced, params replicated)
          2 — + gradients pinned to the zero specs: the update runs on
              grad shards and the grad sync lowers to all-reduce+slice,
              which the TPU ReduceScatterCreator pass fuses into a true
              reduce-scatter (half the sync bytes); updated params
              all-gather back to their param specs
          3 — + parameters THEMSELVES stored sharded; XLA all-gathers
              weights at use sites and frees them after use

        monitor_health=True appends the training-health vector (global
        grad norm, param norm, update ratio — jit/api.py
        HealthMonitorMixin) to the compiled SPMD program, replicated
        over the mesh, resolved on the async is_ready-gated path."""
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.accumulate_steps = accumulate_steps
        self.sharding_stage = int(sharding_stage)
        if self.sharding_stage not in (1, 2, 3):
            raise ValueError(f"sharding_stage must be 1|2|3, got "
                             f"{sharding_stage}")
        self._step_i = 0
        # GradScaler state rides inside the compiled step (donated, like
        # params/opt state); replicated over the mesh
        self.scaler = scaler
        self.scaler_state = scaler.init_jit_state() if scaler is not None \
            else {}
        self.retraces = 0
        self.compile_s = 0.0
        self.last_compile_s = None
        self._init_health(monitor_health)

        params, buffers = state_arrays(model)
        if param_dtype is not None:
            from ...framework.dtype import convert_dtype
            dt = convert_dtype(param_dtype)
            params = {k: v.astype(dt) if jnp.issubdtype(
                v.dtype, jnp.floating) else v for k, v in params.items()}
        self.param_specs = _collect_specs(model, params)
        self.zero_specs = {
            k: _zero_spec(self.param_specs[k], mesh, v)
            for k, v in params.items()}
        # stage 3: parameters live sharded over 'sharding'; XLA
        # all-gathers them at use sites (ZeRO-3 param partitioning)
        store_specs = self.zero_specs if self.sharding_stage >= 3 \
            else self.param_specs
        self.param_shardings = {
            k: NamedSharding(mesh, store_specs[k])
            for k in self.param_specs}
        self.params = {
            k: jax.device_put(v, self.param_shardings[k])
            for k, v in params.items()}
        self.buffers = buffers

        # optimizer state: param spec + ZeRO sharding axis
        def init_state(k, v):
            # init_leaf_state may wrap the tuple with an f32 master copy
            # (multi_precision); master/state leaves all share the param's
            # ZeRO sharding (same shapes)
            st = optimizer.init_leaf_state(v)
            sh = NamedSharding(mesh, _zero_spec(self.param_specs[k], mesh,
                                                v))
            return jax.tree.map(lambda s: jax.device_put(s, sh), st)
        self.opt_state = {k: init_state(k, v)
                          for k, v in self.params.items()}
        # memory-observatory attribution: donated stores are REPLACED
        # each step — getters read the current trees at report time
        _mobs.register("params",
                       self, lambda s: jax.tree.leaves(s.params))
        _mobs.register("opt_state",
                       self, lambda s: jax.tree.leaves(s.opt_state))

        # batch dim over dp; with a sequence-parallel mesh (sp>1), the
        # sequence dim is sharded over 'sp' too — ring attention inside
        # the model consumes it without gathering (long-context path)
        sp_deg = mesh.shape.get("sp", 1)
        self.batch_sharding = NamedSharding(
            mesh, P(("dp",), "sp") if sp_deg > 1 else P(("dp",)))
        self._dp_only = NamedSharding(mesh, P(("dp",)))
        loss_sharding = NamedSharding(mesh, P())

        model_ref = model
        opt = optimizer
        stage = self.sharding_stage
        zero_shardings = {k: NamedSharding(mesh, s)
                          for k, s in self.zero_specs.items()}
        # per-leaf epilogue metadata, shared by the fused kernels and
        # the tree path (defaults are trivial: historical numerics)
        (self._leaf_meta, self._need_clip_tree, self._decay_mask_tree,
         self._lr_scale_tree) = epilogue_leaf_meta(model, optimizer,
                                                   self.params)
        # fused multi-tensor epilogue over PER-SHARD dtype buckets:
        # every leaf's ZeRO shard flattens into its device-local bucket,
        # the kernels run on local contiguous buffers, and ONE psum (of
        # norm-weighted partial sums) yields the global grad norm
        self._fused = self._build_fused(fused_update)
        if self._fused is not None:
            from ...nn.clip import ClipGradByGlobalNorm
            lay = self._fused.layout
            master_keys = {
                key for key, leaf in lay.leaf_order
                if isinstance(self.opt_state[leaf.name], dict)}
            # PER-DEVICE bytes (local shards), matching the per-device
            # cost_analysis the step record's bytes come from
            self._epilogue_bytes = self._fused.bytes_per_step(
                scaling=scaler is not None and scaler.is_enable(),
                need_norm=bool(monitor_health) or isinstance(
                    optimizer._grad_clip, ClipGradByGlobalNorm),
                master_keys=master_keys)
            # hybrid packs grads/params/opt into local buckets each
            # step inside the shard_map (states stay tree-sharded at
            # rest): account that traffic too
            pack_elems = sum(b.total * b.dtype.itemsize
                             for b in lay.buckets.values())
            n_state = self._fused.spec["n_moments"] + 1 + (
                1 if master_keys else 0)
            self._epilogue_bytes += 2 * (n_state + 1) * pack_elems

        def loss_of(ps, bufs, key, micro):
            def run(inputs):
                from ...jit.api import (reset_aux_losses,
                                        collect_aux_losses)
                reset_aux_losses(model_ref)
                out = functional_call(model_ref, ps, bufs, inputs[:-1],
                                      rng_key=key, training=True)
                tgt = Tensor(inputs[-1])
                l = loss_fn(out if isinstance(out, Tensor) else Tensor(out),
                            tgt)
                l = l.value if isinstance(l, Tensor) else l
                aux = collect_aux_losses(model_ref)
                return l if aux is None else l + aux.astype(l.dtype)
            if recompute:
                run = jax.checkpoint(run)
            return run(micro)

        scaler_ref = scaler
        mon_health = self.monitor_health

        def step_fn(params_, opt_state_, scaler_state_, bufs, key, lr,
                    step_i, *batch):
            scaling = scaler_ref is not None and scaler_ref.is_enable()
            scale = scaler_state_["scale"] if scaling else None

            def objective(ps, micro):
                l = loss_of(ps, bufs, key, micro)
                return l.astype(jnp.float32) * scale if scaling else l

            if accumulate_steps > 1:
                micros = [jnp.stack(jnp.split(b, accumulate_steps, axis=0))
                          for b in batch]

                def acc_body(carry, micro):
                    loss_sum, grads_sum = carry
                    l, g = jax.value_and_grad(
                        lambda ps: objective(ps, micro))(params_)
                    return (loss_sum + l,
                            jax.tree.map(jnp.add, grads_sum, g)), None

                zeros = jax.tree.map(jnp.zeros_like, params_)
                (loss_sum, grads), _ = jax.lax.scan(
                    acc_body, (jnp.zeros((), jnp.float32), zeros),
                    tuple(micros))
                loss = loss_sum / accumulate_steps
                grads = jax.tree.map(lambda g: g / accumulate_steps, grads)
            else:
                loss, grads = jax.value_and_grad(
                    lambda ps: objective(ps, batch))(params_)

            if scaling:
                loss = loss / scale

            if self._fused is not None:
                # fused multi-tensor epilogue: unscale + ONE psum'd
                # global norm + clip + update, as per-shard bucket
                # kernels under shard_map (see _fused_finish)
                new_params, new_state, new_scaler_state, aux = \
                    self._fused_finish(grads, params_, opt_state_,
                                       scaler_state_, lr, step_i)
            else:
                if scaling:
                    grads, found_inf, new_scaler_state = \
                        scaler_ref.jit_unscale_and_update(scaler_state_,
                                                          grads)
                else:
                    found_inf, new_scaler_state = None, scaler_state_

                if stage >= 2:
                    # ZeRO-2: pin gradients to the zero specs — the SPMD
                    # partitioner then lowers dp grad sync as
                    # reduce-scatter (each rank keeps only its grad
                    # shard) instead of all-reduce, and the optimizer
                    # update below runs on shards (ref
                    # sharding_stage2.py:43)
                    grads = jax.lax.with_sharding_constraint(
                        grads, zero_shardings)

                from ...nn.clip import (clip_grads_tree, global_grad_norm,
                                        ClipGradByGlobalNorm)
                gn = None
                if mon_health or isinstance(opt._grad_clip,
                                            ClipGradByGlobalNorm):
                    # computed ONCE, shared by the clip factor and the
                    # health vector's grad_norm (no second traversal)
                    gn = global_grad_norm(grads, self._need_clip_tree)
                grads = clip_grads_tree(grads, opt._grad_clip,
                                        need_clip=self._need_clip_tree,
                                        global_norm=gn)
                new_params, new_state = opt.apply_gradients_tree(
                    params_, grads, opt_state_, lr, step_i,
                    found_inf=found_inf,
                    decay_mask=self._decay_mask_tree,
                    lr_scale=self._lr_scale_tree)
                aux = {"grad_norm": gn, "found_inf": found_inf}
                if mon_health:
                    self._tree_health_aux(aux, params_, new_params)
                    if gn is not None and \
                            self._need_clip_tree is not None:
                        # leaves the need_clip mask keeps out of the
                        # norm must still trip health found_inf
                        nonfin = ~jnp.isfinite(gn)
                        for k, g in grads.items():
                            if not self._need_clip_tree.get(k, True):
                                nonfin = nonfin | jnp.any(~jnp.isfinite(
                                    g.astype(jnp.float32)))
                        aux["nonfinite"] = nonfin
            if mon_health:
                health = self._health_vec(loss, aux)
                return loss, health, new_params, new_state, \
                    new_scaler_state
            return loss, new_params, new_state, new_scaler_state

        # mirror each state leaf's structure (tuple, or the
        # {master, state} dict init_leaf_state builds for multi_precision)
        state_shardings = {
            k: jax.tree.map(
                lambda _s, _sh=NamedSharding(
                    mesh, _zero_spec(self.param_specs[k], mesh,
                                     self.params[k])): _sh,
                self.opt_state[k])
            for k in self.opt_state}
        scaler_shardings = jax.tree.map(
            lambda _: NamedSharding(mesh, P()), self.scaler_state)
        out_shardings = (loss_sharding, self.param_shardings,
                         state_shardings, scaler_shardings)
        if mon_health:  # health vector rides replicated, like the loss
            out_shardings = (loss_sharding, NamedSharding(mesh, P()),
                             *out_shardings[1:])
        self._jitted = jax.jit(
            step_fn,
            donate_argnums=(0, 1, 2) if donate else (),
            out_shardings=out_shardings)
        # AOT executables keyed by batch signature (jit.api.aot_compile):
        # trace/compile phases timed, persistent-cache hit observed,
        # cost_analysis free
        self._exec = {}

    # -- fused per-shard epilogue ---------------------------------------
    def _build_fused(self, fused_update):
        """A FusedEpilogue over the LOCAL (ZeRO-shard) leaf shapes, or
        None -> per-leaf tree path. The bucket layout is built from each
        leaf's `zero_spec` shard shape — the update always runs on
        optimizer-state shards (ZeRO semantics for every stage); leaves
        replicated over some mesh axes carry a norm_weight of
        1/replication so the ONE global-norm psum does not count a
        replica per device."""
        import os
        if fused_update is None:
            fused_update = os.environ.get(
                "PADDLE_TPU_FUSED_UPDATE", "1") != "0"
        if not fused_update or not self.params:
            return None
        spec = self.optimizer.fused_spec()
        if spec is None:
            return None
        from ...nn.clip import ClipGradByGlobalNorm, ClipGradByValue
        clip = self.optimizer._grad_clip
        if clip is not None and not isinstance(
                clip, (ClipGradByGlobalNorm, ClipGradByValue)):
            return None
        if not all(jnp.issubdtype(v.dtype, jnp.floating)
                   for v in self.params.values()):
            return None
        from ...ops.pallas.fused_update import (BucketLayout,
                                                FusedEpilogue)
        mesh = self.mesh
        leaves, meta = [], {}
        for k, v in self.params.items():
            zspec = self.zero_specs[k]
            lshape = NamedSharding(mesh, zspec).shard_shape(v.shape)
            axes = set()
            for d in zspec:
                if d is None:
                    continue
                axes.update(d if isinstance(d, (tuple, list)) else (d,))
            sharded = int(np.prod([mesh.shape[a] for a in axes])) \
                if axes else 1
            rep = mesh.size // sharded
            leaves.append((k, lshape, v.dtype))
            meta[k] = dict(self._leaf_meta[k], norm_weight=1.0 / rep)
        layout = BucketLayout(leaves, meta=meta)
        epi = FusedEpilogue(layout, spec)
        epi.set_psum_axes(tuple(mesh.axis_names))
        return epi

    def _fused_finish(self, grads, params, opt_state, scaler_state, lr,
                      step_i):
        """The fused epilogue as ONE shard_map region: every device
        packs its local ZeRO shards into dtype buckets, runs the two
        Pallas passes, and the global grad norm / found_inf / health
        sums reduce with one psum (+pmax) — then the per-leaf tree comes
        back out and the jit-level out_shardings re-gather parameters to
        their storage layout (an all-gather for stage < 3, a no-op for
        stage 3 where storage IS the zero layout)."""
        epi = self._fused
        lay = epi.layout
        scaler = self.scaler
        clip = self.optimizer._grad_clip
        mon = self.monitor_health
        zero = jnp.float32(0.0)

        def body(grads, params, opt_state, scaler_state, lr, step_i):
            g_store = lay.pack(grads)
            p_store = lay.pack(params)
            o_store = epi.pack_opt_tree(opt_state)
            new_p, new_o, new_sc, aux = epi.finish(
                g_store, p_store, o_store, lr, step_i, scaler=scaler,
                scaler_state=scaler_state, clip=clip, with_stats=mon)
            found = aux["found_inf"]
            aux_vec = jnp.stack([
                aux["grad_norm"],
                found.astype(jnp.float32) if found is not None
                else jnp.float32(-1.0),
                aux.get("param_sumsq", zero),
                aux.get("update_sumsq", zero)])
            return (lay.unpack(new_p), epi.state_view(new_o), new_sc,
                    aux_vec)

        zspecs = {k: self.zero_specs[k] for k in params}
        state_specs = {
            k: jax.tree.map(lambda _, s=self.zero_specs[k]: s,
                            opt_state[k])
            for k in opt_state}
        scaler_specs = jax.tree.map(lambda _: P(), scaler_state)
        new_params, new_state, new_sc, aux_vec = shard_map(
            body, mesh=self.mesh,
            in_specs=(zspecs, zspecs, state_specs, scaler_specs, P(),
                      P()),
            out_specs=(zspecs, state_specs, scaler_specs, P()),
            check_vma=False)(grads, params, opt_state, scaler_state, lr,
                             step_i)
        found = None
        if scaler is not None and scaler.is_enable():
            found = aux_vec[1] > 0
        aux = {"grad_norm": aux_vec[0], "found_inf": found,
               "param_sumsq": aux_vec[2], "update_sumsq": aux_vec[3]}
        return new_params, new_state, new_sc, aux

    def input_sharding(self, arr):
        """Sharding the compiled step expects for a batch leaf (batch dim
        over 'dp', sequence over 'sp' when sequence-parallel). The device
        prefetch ring (io/device_prefetch.py) places H2D copies with this
        while the previous step computes, so `_prep` below finds the
        arrays already resident and sharded."""
        return self.batch_sharding if arr.ndim >= 2 else self._dp_only

    def _prep(self, batch, step_i):
        """(sig, full arg tuple) for one dispatch — the ONE place the
        batch is sharded and the signature built: __call__ and the
        inspection paths must agree exactly, because the cached
        executable bakes the input shardings. An array that already
        carries its target sharding (prefetch ring) passes through
        without a fresh device_put."""
        arrays = []
        for b in batch:
            a = b.value if isinstance(b, Tensor) else jnp.asarray(b)
            sh = self.input_sharding(a)
            if getattr(a, "sharding", None) != sh:
                a = jax.device_put(a, sh)
            arrays.append(a)
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        args = (self.params, self.opt_state, self.scaler_state,
                self.buffers, split_key(),
                jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                step_i, *arrays)
        return sig, args

    def _warm_submit(self, sig, args, n_batch, inline=False):
        """Single-flight compile of this signature's SPMD executable
        (jit/warm.py submit_cached) — shared by `warm()` (background)
        and the dispatch/inspection paths (`inline=True`: compile on
        the calling thread rather than queue behind background warms),
        so a warm in flight is always joined, never duplicated."""
        return _warm.submit_cached(
            self._exec, sig, "fleet.hybrid_step",
            lambda: aot_compile(self._jitted, args,
                                tag="fleet.hybrid_step",
                                arg_names=_step_arg_names(n_batch)),
            inline=inline)

    def warm(self, *batch):
        """Start a BACKGROUND AOT compile of the hybrid SPMD executable
        for exactly this batch signature (same `_prep`, same shardings
        and donation as dispatch — warming adds zero executables beyond
        steady state) and return a `jit.warm.WarmHandle`. The first
        `__call__` with this signature joins the in-flight compile."""
        sig, args = self._prep(batch, self._step_i + 1)
        return self._warm_submit(sig, args, len(batch))

    def set_tree_state(self, params=None, opt_state=None):
        """Load per-leaf state back into the step (checkpoint restore:
        distributed/checkpoint.py) — the sharded counterpart of
        TrainStep.set_tree_state: every array is device_put DIRECTLY
        onto its storage sharding (params to `param_shardings`,
        optimizer state to its live leaf's ZeRO placement), so a
        resume lands dp/mp-sharded without materializing the full
        tree on one host."""
        if params is not None:
            self.params = {
                k: jax.device_put(v, self.param_shardings[k])
                for k, v in params.items()}
        if opt_state is not None:
            self.opt_state = {
                k: jax.tree.map(
                    lambda new, cur: jax.device_put(new, cur.sharding),
                    opt_state[k], self.opt_state[k])
                for k in self.opt_state}

    def __call__(self, *batch):
        self._step_i += 1
        if _fault.active():  # fault drills only; two dict reads when off
            batch = fire_step_faults(self, batch)
        sig, args = self._prep(batch, self._step_i)
        probe = device_probe_open(self, self._step_i)
        _flight.heartbeat(self._step_i)  # watchdog liveness pulse
        _stat.begin_span("fleet.hybrid_step")
        try:
            entry = self._exec.get(sig)
            compiled_now = entry is None
            if compiled_now:
                entry = self._warm_submit(sig, args, len(batch),
                                          inline=True).result()
            compiled, info = entry
            count_train_use(self, info)
            try:
                if getattr(self, "_oom_fault", False):
                    # oom@train.step soft fault: raise the synthetic
                    # exhaustion inside the real dispatch try (same
                    # contract as TrainStep._dispatch)
                    self._oom_fault = False
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: injected OOM "
                        "(oom@train.step fault): failed to allocate "
                        "request for 8.00GiB on device")
                out = compiled(*args)
            except (FloatingPointError, RuntimeError) as e:
                if _mobs.is_oom(e):
                    raise _mobs.oom_error(
                        e, site="fleet.hybrid_step") from e
                # jax_debug_nans found a non-finite value: flight-record
                # and write a debug bundle before re-raising (same
                # contract as TrainStep._dispatch, incl. the donated-
                # buffer re-run surfacing as a deleted-array error)
                donated_rerun = (
                    isinstance(e, RuntimeError)
                    and jax.config.jax_debug_nans
                    and "deleted" in str(e))
                if isinstance(e, RuntimeError) and not donated_rerun:
                    raise
                _flight.record_event("nan_detected",
                                     where="fleet.hybrid_step",
                                     step=int(self._step_i),
                                     error=str(e)[:300])
                _flight.dump("nan", exc=e)
                if donated_rerun:
                    raise FloatingPointError(
                        "jax_debug_nans detected a non-finite value in "
                        "the compiled fleet.hybrid_step program (the "
                        "op-level re-run could not localize it because "
                        "the step donates its buffers; build with "
                        "donate=False to localize)") from e
                raise
            if self.monitor_health:
                loss, health, self.params, self.opt_state, \
                    self.scaler_state = out
                self._queue_health(self._step_i, health)
            else:
                loss, self.params, self.opt_state, self.scaler_state = out
        finally:
            dispatch_s = _stat.end_span()
        device_probe_close(self, self._step_i, probe, loss, info,
                           compiled_now=compiled_now)
        export_step_metrics(self, dispatch_s, info, compiled_now)
        # non-blocking handle (see jit/deferred.py): the fit loop keeps
        # dispatching while the loss streams back
        return DeferredLoss(loss)

    def cost_analysis(self, *batch):
        """XLA cost report for this batch signature's SPMD executable
        (per-device flops/bytes; free once the step has run, and never
        touching the retrace counters)."""
        return _cost.cost_analysis(self._executable(*batch))

    def flops(self, *batch):
        """Per-step per-device FLOPs of the compiled SPMD program."""
        return _cost.executable_flops(self._executable(*batch))

    def _executable(self, *batch):
        sig, args = self._prep(batch, self._step_i + 1)
        entry = self._exec.get(sig)
        if entry is None:
            entry = self._warm_submit(sig, args, len(batch),
                                      inline=True).result()
        return entry[0]

    def sync_to_model(self):
        named = dict(self.model.named_parameters())
        with no_grad():
            for k, v in self.params.items():
                named[k]._slot = _Slot(v)
        if self.scaler is not None and self.scaler_state:
            self.scaler.sync_from_jit_state(self.scaler_state)

    def compiled_text(self, *batch):
        """Optimized HLO for inspection/tests; reuses the AOT executable
        cache — no extra compile once the step has run."""
        return self._executable(*batch).as_text()
