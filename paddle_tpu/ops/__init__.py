"""paddle_tpu.ops — hand-written Pallas TPU kernels for the hot paths
(SURVEY.md §6): flash attention, fused layer_norm, softmax-cross-entropy.

Kernels run natively on TPU; on CPU (tests) they run in Pallas interpret
mode or fall back to the XLA composition.
"""
import os

import jax

_FLASH_ENV = os.environ.get("PADDLE_TPU_FLASH", "auto")


def _on_tpu():
    try:
        return jax.default_backend() in ("tpu",)
    except Exception:
        return False


def flash_attention_available():
    if _FLASH_ENV == "0":
        return False
    try:
        from .pallas import flash_attention as _  # noqa
        return _on_tpu() or _FLASH_ENV == "interpret"
    except Exception:
        return False


def flash_attention(q, k, v, causal=False, scale=None):
    from .pallas.flash_attention import flash_attention as fa
    return fa(q, k, v, causal=causal, scale=scale)


def fused_layer_norm_available():
    return _on_tpu()


def fused_layer_norm(x, weight, bias, eps=1e-5):
    from .pallas.layer_norm import layer_norm as ln
    return ln(x, weight, bias, eps)


from .block_sparse import (block_sparse_attention,  # noqa: E402
                           block_sparse_attention_arrays,
                           local_strided_pattern)

from .paged_attention import PagedKVCache, paged_attention  # noqa: E402


def ragged_paged_attention(*args, **kwargs):
    """Mixed prefill+decode paged attention (lazy import: the Pallas
    module stays off the package-import path, like flash_attention)."""
    from .pallas.paged_attention import ragged_paged_attention as rpa
    return rpa(*args, **kwargs)


def ragged_work_plan(bounds, page_size):
    from .pallas.paged_attention import ragged_work_plan as rwp
    return rwp(bounds, page_size)
