"""paddle.dataset.movielens — MovieLens-1M ratings corpus, legacy
reader API.

Parity: /root/reference/python/paddle/dataset/movielens.py (ml-1m.zip
with ::-separated movies/users/ratings .dat files; samples are
user.value() + movie.value() + [[scaled rating]]).
"""
import functools
import os
import re
import zipfile

import numpy as np

from .common import DATA_HOME

__all__ = []

age_table = [1, 18, 25, 35, 45, 50, 56]


def _zip_path():
    return os.path.join(DATA_HOME, "movielens", "ml-1m.zip")


class MovieInfo:
    """Movie id, title and categories."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index,
                [CATEGORIES_DICT[c] for c in self.categories],
                [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    """User id, gender, age bucket and job."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), "
                f"gender({'M' if self.is_male else 'F'}), "
                f"age({age_table[self.age]}), job({self.job_id})>")


MOVIE_INFO = None
MOVIE_TITLE_DICT = None
CATEGORIES_DICT = None
USER_INFO = None


def __initialize_meta_info__():
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO
    fn = _zip_path()
    if not os.path.exists(fn):
        raise FileNotFoundError(
            f"movielens: no network access — place ml-1m.zip at {fn}")
    if MOVIE_INFO is None:
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        MOVIE_INFO, title_words, categories = {}, set(), set()
        with zipfile.ZipFile(fn) as package:
            with package.open("ml-1m/movies.dat") as f:
                for line in f:
                    movie_id, title, cats = line.decode(
                        "latin").strip().split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    title = pattern.match(title).group(1)
                    MOVIE_INFO[int(movie_id)] = MovieInfo(
                        movie_id, cats, title)
                    title_words.update(
                        w.lower() for w in title.split())
            MOVIE_TITLE_DICT = {w: i for i, w in enumerate(title_words)}
            CATEGORIES_DICT = {c: i for i, c in enumerate(categories)}
            USER_INFO = {}
            with package.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = line.decode(
                        "latin").strip().split("::")
                    USER_INFO[int(uid)] = UserInfo(uid, gender, age, job)
    return fn


def __reader__(rand_seed=0, test_ratio=0.1, is_test=False):
    fn = __initialize_meta_info__()
    np.random.seed(rand_seed)
    with zipfile.ZipFile(fn) as package:
        with package.open("ml-1m/ratings.dat") as f:
            for line in f:
                if (np.random.random() < test_ratio) != is_test:
                    continue
                uid, mov_id, rating, _ = line.decode(
                    "latin").strip().split("::")
                rating = float(rating) * 2 - 5.0
                yield (USER_INFO[int(uid)].value()
                       + MOVIE_INFO[int(mov_id)].value() + [[rating]])


def __reader_creator__(**kwargs):
    return lambda: __reader__(**kwargs)


train = functools.partial(__reader_creator__, is_test=False)
test = functools.partial(__reader_creator__, is_test=True)


def get_movie_title_dict():
    __initialize_meta_info__()
    return MOVIE_TITLE_DICT


def max_movie_id():
    __initialize_meta_info__()
    return max(MOVIE_INFO.values(), key=lambda m: m.index).index


def max_user_id():
    __initialize_meta_info__()
    return max(USER_INFO.values(), key=lambda u: u.index).index


def max_job_id():
    __initialize_meta_info__()
    return max(USER_INFO.values(), key=lambda u: u.job_id).job_id


def movie_categories():
    __initialize_meta_info__()
    return CATEGORIES_DICT


def user_info():
    __initialize_meta_info__()
    return list(USER_INFO.values())


def movie_info():
    __initialize_meta_info__()
    return list(MOVIE_INFO.values())


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip",
             "movielens", None)
