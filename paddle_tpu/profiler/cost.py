"""Derived performance accounting: XLA cost analysis → FLOPs/bytes/MFU.

"Operator Fusion in XLA: Analysis and Evaluation" (PAPERS.md) identifies
XLA's own cost analysis as the per-executable source of truth for FLOPs
and bytes moved — exactly the denominator-side evidence a bench attempt
or a Profiler.summary() needs. jax exposes it as
`compiled.cost_analysis()`; this module normalizes the return shape
(list-of-dicts on some jaxlibs, dict on others), maps device kinds to
nominal bf16 peak FLOP/s, and derives MFU.

TrainStep/HybridTrainStep dispatch through explicitly compiled
executables (jit/api.py), so the analysis here is free — no re-lower, no
re-compile.
"""

__all__ = ["cost_analysis", "executable_flops", "executable_bytes",
           "device_peak_flops", "mfu", "PEAK_BF16_FLOPS"]

# nominal bf16 peak per chip generation (matmul TFLOP/s), keyed by
# substrings of jax.Device.device_kind
PEAK_BF16_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def cost_analysis(compiled):
    """XLA's analytical cost report for a compiled executable as a plain
    dict ({} when the backend exposes none). Keys of interest: 'flops',
    'bytes accessed', plus per-operand 'bytes accessed{N}' entries."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return dict(ca)
    except Exception:
        return {}


def executable_flops(compiled):
    """Per-execution FLOPs of a compiled executable (0.0 if unknown)."""
    return float(cost_analysis(compiled).get("flops", 0.0))


def executable_bytes(compiled):
    """Bytes accessed per execution (0.0 if unknown)."""
    return float(cost_analysis(compiled).get("bytes accessed", 0.0))


def device_peak_flops(device=None, default=0.0):
    """Nominal bf16 peak FLOP/s for the attached chip generation;
    `default` (0.0 = unknown) for backends without a table entry (CPU).
    Touches jax.devices() — callers on the no-backend-init path must
    guard."""
    try:
        import jax
        d = device if device is not None else jax.devices()[0]
        kind = d.device_kind.lower()
    except Exception:
        return default
    for key, peak in PEAK_BF16_FLOPS.items():
        if key in kind:
            return peak
    return default


def mfu(flops_per_step, step_time_s, peak_flops=None):
    """Model FLOPs utilization: achieved FLOP/s over the chip's nominal
    peak. 0.0 when any input is unknown (missing cost analysis, CPU
    backend, zero step time)."""
    if peak_flops is None:
        peak_flops = device_peak_flops()
    if not flops_per_step or not step_time_s or not peak_flops:
        return 0.0
    return float(flops_per_step) / float(step_time_s) / float(peak_flops)
