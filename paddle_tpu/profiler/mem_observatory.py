"""The memory observatory: tagged device-memory ledger, pool
fragmentation telemetry, and OOM forensics.

Fifth observatory sibling (compile / serve / dist / fleet), built
because every other sibling measures TIME and none measures BYTES: the
capacity claims the repo makes — SSM concurrent-sequence ratios,
projected-admittable-pages admission, the quantized-KV headroom the
ROADMAP queues next — are analytic page/slot math, never reconciled
against what HBM actually holds, and an allocator OOM dies as a bare
XLA ``RESOURCE_EXHAUSTED`` with no attribution. Four pieces:

- **Tagged allocation ledger** — long-lived device-array holders
  register under stable tags (``params`` / ``opt_state`` from the train
  steps' flat stores, ``kv_pool.<engine>`` / ``draft_pool`` /
  ``ssm_state`` from the serving cache pools, ``ckpt_snapshot`` from
  the checkpoint writer's detached copies, ``prefetch`` from the device
  prefetch ring) via a BOUNDED weakref registry: `register(tag, owner,
  getter)` holds the owner weakly and asks the getter for the CURRENT
  arrays at report time (so donated/replaced buffers stay attributed),
  `register_arrays(tag, arrays)` holds transient buffers as per-array
  weakrefs (a dead snapshot drops to zero bytes by itself). Nothing in
  the ledger extends any buffer's lifetime. `mem_report()` splits live
  `jax.Device.memory_stats()` bytes into attributed (deduplicated over
  shared pools — a disaggregated pair registering one pool twice counts
  it once) vs unattributed, the latter bounded by the compile ledger's
  per-executable `memory_analysis` peaks (temp/scratch is the only
  legitimate unattributed resident). On backends with no allocator
  stats (CPU) the report degrades to ledger arithmetic, stamped
  ``measured: false`` — the attribution bound still holds.

- **Periodic ``kind:"memory"`` records** — cadence-gated like
  rankstat/kvcache (first emission per source always, then every
  PADDLE_TPU_MEMORY_EVERY-th train step — default 16, 0 disables — and
  every kv_snapshot_every-th serving step, co-located with the kvcache
  snapshot): per-tag bytes, device total/peak, pool occupancy, and for
  page pools a MEASURED fragmentation metric — the free-list's
  contiguous-run histogram and largest-contiguous-claimable run vs
  total free (`fragmentation = 1 - largest_run/free`), computed from
  the pool's actual free page ids, not claimed from geometry.

- **OOM forensics** — the dispatch choke points (jit/api dispatch,
  serving `_ragged_step`, checkpoint snapshot) catch
  ``RESOURCE_EXHAUSTED`` and route it through `oom_error(exc, site)`:
  flight-record a ``device_oom`` event, dump a debug bundle whose
  ``mem_state.json`` carries the full tag ledger, per-pool pool_stats,
  per-executable memory_analysis peaks, and the requested size parsed
  from the XLA message — then return a framework `DeviceOOMError`
  naming the top-3 holders, so the failure says WHO held the memory,
  not just that it ran out.

- **Measured-bytes admission feed** — `pool_hbm(cache)` turns a cache
  pool's device arrays into measured byte gauges (total / free /
  headroom, page-granular for paged pools, slot-granular for
  recurrent) that `GenerationEngine.load_report()` and the router's
  fleet rollup export as ``hbm_free_bytes`` / ``hbm_headroom_bytes``
  next to the analytic page math; `FleetPressure` edge-triggers a
  ``memory_pressure`` event when the fleet's measured headroom sits
  below the PADDLE_TPU_MEM_WATERMARK fraction (default 0.1) for K
  consecutive snapshots.

Every emit helper never raises — memory telemetry must never take down
the engine. Pure host arithmetic throughout (array `.nbytes` is
metadata, `memory_stats()` is an allocator query — no device syncs);
the module is fenced whole by tools/check_no_hot_sync.py. See
docs/OBSERVABILITY.md "The memory observatory".
"""
import collections
import json
import os
import re
import threading
import weakref

from . import flight_recorder as _fr
from . import monitor as _monitor

__all__ = ["DeviceOOMError", "register", "register_arrays", "deregister",
           "registered_tags", "tag_bytes", "ledger", "mem_report",
           "fragmentation", "pool_hbm", "maybe_memory", "record_memory",
           "records_tail", "is_oom", "parse_requested_bytes",
           "oom_error", "mem_state", "reset", "MAX_TAGS", "MEMORY_RING"]

MAX_TAGS = 64     # registry bound: oldest tag evicted beyond this
MEMORY_RING = 256  # emitted memory records kept for bundle/host_stats

_lock = threading.RLock()
# tag -> _TagEntry; OrderedDict so eviction drops the oldest registration
_tags = collections.OrderedDict()
_records = collections.deque(maxlen=MEMORY_RING)
_state = {
    "emitted": set(),   # cadence sources that have emitted once
    "peaks": {},        # tag -> peak bytes observed at any report
    "last_oom": None,   # context of the most recent OOM (mem_state.json)
}
_state_registered = [False]


class DeviceOOMError(RuntimeError):
    """A device allocation failed (XLA ``RESOURCE_EXHAUSTED``) — raised
    by the instrumented dispatch choke points AFTER the memory
    observatory dumped a debug bundle. Carries the forensics inline:
    `site` (which choke point), `requested_bytes` (parsed from the XLA
    message, 0 when unparseable), `top_holders` ([(tag, bytes)] — the
    ledger's three largest), and `bundle_dir` (the dumped bundle's
    path, None when dumping was off/failed)."""

    def __init__(self, message, site=None, requested_bytes=0,
                 top_holders=None, bundle_dir=None):
        super().__init__(message)
        self.site = site
        self.requested_bytes = int(requested_bytes)
        self.top_holders = list(top_holders or [])
        self.bundle_dir = bundle_dir


class _TagEntry:
    __slots__ = ("owner", "getter", "refs")

    def __init__(self, owner=None, getter=None, refs=None):
        self.owner = owner    # weakref to the holder (getter mode)
        self.getter = getter  # owner -> iterable of device arrays
        self.refs = refs      # [weakref(array)] (transient mode)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _default_getter(owner):
    """Registration without an explicit getter asks the owner for its
    arrays: `device_arrays()` (the cache pools' surface) or the owner
    itself as an iterable."""
    fn = getattr(owner, "device_arrays", None)
    if callable(fn):
        return fn()
    return owner


def register(tag, owner, getter=None):
    """Attribute `owner`'s device arrays to `tag`. The owner is held by
    WEAKREF and `getter(owner)` is called at report time for the
    CURRENT arrays — so functionally-replaced buffers (donated train
    stores) stay attributed without re-registration, and a collected
    owner silently leaves the ledger. `getter=None` uses the owner's
    `device_arrays()` method (or iterates the owner). Re-registering a
    tag replaces it; the registry is bounded at MAX_TAGS (oldest
    evicted). Never raises."""
    try:
        entry = _TagEntry(owner=weakref.ref(owner),
                          getter=getter or _default_getter)
        with _lock:
            _tags.pop(tag, None)
            _tags[tag] = entry
            while len(_tags) > MAX_TAGS:
                _tags.popitem(last=False)
    except Exception:
        pass  # telemetry must never take down the registrant


def register_arrays(tag, arrays):
    """Attribute a TRANSIENT buffer set (a checkpoint snapshot, the
    prefetch ring's staged batch) to `tag` via per-array weakrefs: when
    the holder drops the buffers, the tag's bytes fall to zero on their
    own — the ledger never extends a snapshot's lifetime. Re-registering
    replaces the previous set (the prefetch ring re-registers each
    staged batch). Never raises."""
    try:
        refs = []
        for a in arrays:
            try:
                refs.append(weakref.ref(a))
            except TypeError:
                continue  # non-weakrefable leaf (python scalar): skip
        entry = _TagEntry(refs=refs)
        with _lock:
            _tags.pop(tag, None)
            _tags[tag] = entry
            while len(_tags) > MAX_TAGS:
                _tags.popitem(last=False)
    except Exception:
        pass


def deregister(tag):
    """Drop one tag from the ledger (tests / explicit teardown)."""
    with _lock:
        _tags.pop(tag, None)


def registered_tags():
    """Registered tag names, registration order (diagnostics/tests)."""
    with _lock:
        return list(_tags)


def _live_arrays(entry):
    """The entry's CURRENT live device arrays ([] when the owner died
    or the getter refused)."""
    try:
        if entry.refs is not None:
            return [a for a in (r() for r in entry.refs) if a is not None]
        owner = entry.owner()
        if owner is None:
            return []
        return [a for a in entry.getter(owner)
                if getattr(a, "nbytes", None) is not None]
    except Exception:
        return []


def _snapshot_entries():
    with _lock:
        return list(_tags.items())


def ledger():
    """{tag: {"bytes", "arrays", "alive"}} — each tag's own view of its
    registered arrays (NO cross-tag dedup: two tags sharing one pool
    both report it; `mem_report()` dedups for the attribution total).
    Dead tags (owner collected, every transient ref dead) report
    alive=False with zero bytes."""
    out = {}
    for tag, entry in _snapshot_entries():
        arrays = _live_arrays(entry)
        alive = bool(arrays) or (entry.owner is not None
                                 and entry.owner() is not None)
        out[tag] = {"bytes": sum(int(a.nbytes) for a in arrays),
                    "arrays": len(arrays), "alive": alive}
    return out


def tag_bytes():
    """{tag: bytes} over the live ledger (each tag's own view)."""
    return {t: v["bytes"] for t, v in ledger().items()}


def _executable_peak_bytes():
    """Sum over distinct executable tags of the compile ledger's max
    `memory_analysis` peak — the bound on legitimate UNATTRIBUTED
    resident bytes (temp/scratch an executable may hold)."""
    try:
        from . import compile_observatory as _cobs
        peaks = {}
        for r in _cobs.ledger():
            p = float(r.get("peak_memory_bytes", 0.0) or 0.0)  # hot-sync-ok: host dict field from the compile ledger, not a device read
            t = r.get("tag", "?")
            if p > peaks.get(t, 0.0):
                peaks[t] = p
        return int(sum(peaks.values()))
    except Exception:
        return 0


def mem_report(device=None):
    """The attribution split: per-tag ledger bytes, the attributed
    total DEDUPLICATED over shared buffers (id-keyed — a pool
    registered under two tags counts once), and the device totals from
    `jax.Device.memory_stats()`. `measured` is True when the allocator
    answered; on statless backends (CPU) the device totals fall back to
    the ledger sum so the `attributed <= device total` bound the schema
    enforces holds in both modes. `unattributed_bytes` is what the
    ledger cannot name, bounded by `executable_peak_bytes` (the compile
    ledger's temp/scratch peaks). Never raises; pure host reads."""
    tags = {}
    seen = set()
    attributed = 0
    for tag, entry in _snapshot_entries():
        b = 0
        for a in _live_arrays(entry):
            nb = int(a.nbytes)
            b += nb
            key = id(a)
            if key not in seen:
                seen.add(key)
                attributed += nb
        tags[tag] = b
        peaks = _state["peaks"]
        if b > peaks.get(tag, 0):
            peaks[tag] = b
    try:
        from .. import device as _device
        stats = _device._memory_stats(device)
    except Exception:
        stats = {}
    measured = bool(stats)
    in_use = int(stats.get("bytes_in_use", 0))
    peak = int(stats.get("peak_bytes_in_use", 0))
    limit = int(stats.get("bytes_limit", 0))
    if not measured:
        # no allocator stats: the ledger IS the best device-total
        # estimate — attribution trivially sums to total
        in_use = attributed
        peak = max(attributed, max(_state["peaks"].values(), default=0))
    return {
        "measured": measured,
        "tags": tags,
        "attributed_bytes": int(attributed),
        "device_bytes_in_use": int(max(in_use, attributed)),
        "device_peak_bytes": int(max(peak, attributed)),
        "device_bytes_limit": int(limit),
        "unattributed_bytes": int(max(in_use - attributed, 0)),
        "executable_peak_bytes": _executable_peak_bytes(),
    }


# -- pool fragmentation (measured, not claimed) ---------------------------

def _free_page_ids(cache):
    """Sorted snapshot of a paged pool's free page ids (C-level list()
    copy under the pool lock when available — safe from any thread)."""
    free = getattr(cache, "_free", None)
    if free is None:
        return None
    lock = getattr(cache, "lock", None)
    if lock is not None:
        with lock:
            free = list(free)
    else:
        free = list(free)
    return sorted(int(p) for p in free)


def fragmentation(cache):
    """MEASURED fragmentation of a page pool's free list: walk the
    sorted free page ids into contiguous runs, histogram the run
    lengths (power-of-two buckets), and relate the largest contiguous
    claimable run to the total free count —
    ``fragmentation = 1 - largest_run / free_pages`` (0.0 for an empty
    free list or one unbroken run). Hybrid caches report their paged
    half; recurrent pools have no adjacency (every slot is
    interchangeable) and report None. Never raises."""
    try:
        paged = getattr(cache, "paged", None)
        if paged is not None:        # HybridCache -> its paged half
            cache = paged
        if getattr(cache, "strategy", "paged") != "paged":
            return None
        free = _free_page_ids(cache)
        if free is None:
            return None
        runs = []
        for p in free:
            if runs and p == runs[-1][0] + runs[-1][1]:
                runs[-1][1] += 1
            else:
                runs.append([p, 1])
        lengths = [n for _, n in runs]
        hist = {}
        for n in lengths:
            b = 1 << (n - 1).bit_length()  # pow2 bucket the run fits in
            key = str(b)
            hist[key] = hist.get(key, 0) + 1
        largest = max(lengths, default=0)
        n_free = len(free)
        frag = 0.0 if n_free == 0 else 1.0 - largest / n_free
        return {"free_pages": n_free, "free_runs": len(lengths),
                "largest_free_run": int(largest),
                "free_run_histogram": hist,
                "fragmentation": round(max(min(frag, 1.0), 0.0), 6)}
    except Exception:
        return None


def _paged_hbm(cache):
    """(total, free, headroom) bytes of one PagedKVCache, page-granular
    and MEASURED: per-page bytes come from the pool's actual device
    arrays (`sum(nbytes) / n_pages`), not dtype arithmetic; free counts
    free + evictable pages; headroom additionally subtracts outstanding
    admission claims — the same quantities admission reasons in, in
    bytes instead of pages."""
    arrays = cache.device_arrays() if hasattr(cache, "device_arrays") \
        else list(getattr(cache, "k", [])) + list(getattr(cache, "v", []))
    total = sum(int(a.nbytes) for a in arrays)
    n_pages = max(int(getattr(cache, "n_pages", 1)), 1)
    page_bytes = total // n_pages
    free = int(cache.n_free_pages()) + int(cache.n_evictable_pages())
    claims = int(cache.outstanding_claims()) \
        if hasattr(cache, "outstanding_claims") else 0
    headroom = max(free - claims, 0)
    return total, free * page_bytes, headroom * page_bytes, page_bytes


def pool_hbm(cache):
    """Measured byte gauges of one cache pool: {"hbm_total_bytes",
    "hbm_free_bytes", "hbm_headroom_bytes"} (+ "page_bytes" for pools
    with a page surface). Paged pools are page-granular (free +
    evictable pages x measured per-page bytes; headroom subtracts
    outstanding claims), recurrent pools slot-granular (free slots x
    measured per-slot bytes), hybrid pools sum both halves. Never
    raises; returns zeros-shaped dict on refusal."""
    try:
        strategy = getattr(cache, "strategy", "paged")
        if strategy == "hybrid":
            pt, pf, ph, pb = _paged_hbm(cache.paged)
            rt, rf, rh = _recurrent_hbm(cache.recurrent)
            return {"hbm_total_bytes": pt + rt,
                    "hbm_free_bytes": pf + rf,
                    "hbm_headroom_bytes": ph + rh,
                    "page_bytes": pb}
        if strategy == "recurrent":
            rt, rf, rh = _recurrent_hbm(cache)
            return {"hbm_total_bytes": rt, "hbm_free_bytes": rf,
                    "hbm_headroom_bytes": rh}
        pt, pf, ph, pb = _paged_hbm(cache)
        return {"hbm_total_bytes": pt, "hbm_free_bytes": pf,
                "hbm_headroom_bytes": ph, "page_bytes": pb}
    except Exception:
        return {"hbm_total_bytes": 0, "hbm_free_bytes": 0,
                "hbm_headroom_bytes": 0}


def _recurrent_hbm(cache):
    """(total, free, headroom) bytes of one RecurrentStateCache —
    slot-granular, measured from the state pools' device arrays."""
    arrays = cache.device_arrays() if hasattr(cache, "device_arrays") \
        else list(getattr(cache, "conv", [])) \
        + list(getattr(cache, "ssm", []))
    total = sum(int(a.nbytes) for a in arrays)
    slots = max(int(getattr(cache, "n_pages", 1)), 1)
    slot_bytes = total // slots
    with cache.lock:
        free = len(list(cache._free))
        claims = sum(dict(cache._claims).values()) \
            if hasattr(cache, "_claims") else 0
    return total, free * slot_bytes, max(free - claims, 0) * slot_bytes


# -- periodic kind:"memory" records ---------------------------------------

def maybe_memory(step_i, source="train", engine=None, cache=None):
    """Cadence gate for the per-step call sites (`export_step_metrics`):
    emit a memory record on the FIRST step seen for this source and
    then every PADDLE_TPU_MEMORY_EVERY-th (default 16; 0 disables).
    The off-cadence cost is one int modulo + a set lookup."""
    every = _env_int("PADDLE_TPU_MEMORY_EVERY", 16)
    if every <= 0:
        return None
    key = f"{source}.{engine or ''}"
    if key in _state["emitted"] and step_i % every != 0:
        return None
    return record_memory(source=source, step=step_i, engine=engine,
                         cache=cache)


def record_memory(source, step=None, engine=None, cache=None):
    """Build + export ONE `kind:"memory"` record: the full attribution
    split (`mem_report`), and — when a cache pool rides along — its
    occupancy plus the measured fragmentation metric and hbm byte
    gauges. Ringed in the flight recorder always, JSONL when
    PADDLE_TPU_METRICS_FILE is set. Never raises; returns the record
    (None on failure)."""
    try:
        rep = mem_report()
        rec = {
            "source": str(source),
            "step": int(step or 0),
            "measured": bool(rep["measured"]),
            "tags": {t: int(b) for t, b in rep["tags"].items()},
            "attributed_bytes": rep["attributed_bytes"],
            "unattributed_bytes": rep["unattributed_bytes"],
            "device_bytes_in_use": rep["device_bytes_in_use"],
            "device_peak_bytes": rep["device_peak_bytes"],
            "device_bytes_limit": rep["device_bytes_limit"],
            "executable_peak_bytes": rep["executable_peak_bytes"],
        }
        if engine is not None:
            rec["engine"] = str(engine)
        if cache is not None:
            stats = cache.pool_stats()
            rec["cache_strategy"] = str(
                stats.get("cache_strategy", "paged"))
            hbm = pool_hbm(cache)
            rec.update(hbm)
            if rec["cache_strategy"] != "recurrent":
                rec["n_pages"] = int(getattr(cache, "n_pages", 0))
                rec["free_pages"] = int(stats.get("free_pages", 0))
                rec["held_pages"] = int(stats.get("held_pages", 0))
                frag = fragmentation(cache)
                if frag is not None:
                    rec.update(frag)
            if rec["cache_strategy"] != "paged":
                rec["free_slots"] = int(stats.get("free_slots", 0))
                rec["held_slots"] = int(stats.get("held_slots", 0))
                rec["state_bytes_total"] = int(
                    stats.get("state_bytes_total", 0))
        _monitor.gauge("mem.attributed_bytes").set(
            rep["attributed_bytes"])
        _monitor.gauge("mem.unattributed_bytes").set(
            rep["unattributed_bytes"])
        if "fragmentation" in rec:
            _monitor.gauge("mem.kv_fragmentation").set(
                rec["fragmentation"])
        _state["emitted"].add(f"{source}.{engine or ''}")
        _ensure_state_provider()
        _monitor.export_step(rec, kind="memory")
        with _lock:
            _records.append(dict(rec))
        return rec
    except Exception:
        return None


def records_tail():
    """The ring of recent `kind:"memory"` records (oldest first) —
    what `Profiler.export_host_stats` embeds and a debug bundle's
    mem_state.json carries as the trend tail."""
    with _lock:
        return [dict(r) for r in _records]


# -- OOM forensics ---------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "resource_exhausted",
                "Out of memory", "out of memory", "OutOfMemory")

# XLA phrasings: "while trying to allocate 8589934592 bytes",
# "Failed to allocate request for 8.00GiB", "allocating 2.5G ..."
_SIZE_RE = re.compile(
    r"alloca\w*\s+(?:request\s+)?(?:for\s+|of\s+)?"
    r"([\d.]+)\s*([KMGT]i?B?|bytes?|B)\b", re.IGNORECASE)
_UNITS = {"b": 1, "byte": 1, "bytes": 1,
          "k": 1000, "kb": 1000, "kib": 1024,
          "m": 1000**2, "mb": 1000**2, "mib": 1024**2,
          "g": 1000**3, "gb": 1000**3, "gib": 1024**3,
          "t": 1000**4, "tb": 1000**4, "tib": 1024**4}


def is_oom(exc):
    """True when `exc` is a device allocator exhaustion (XLA
    ``RESOURCE_EXHAUSTED`` / out-of-memory phrasing) — the dispatch
    choke points' routing predicate. A DeviceOOMError is already
    forensics-wrapped and answers False (no double wrapping)."""
    if isinstance(exc, DeviceOOMError):
        return False
    msg = f"{type(exc).__name__}: {exc}"
    return any(m in msg for m in _OOM_MARKERS)


def parse_requested_bytes(msg):
    """The allocation size the XLA message names, in bytes (0 when the
    message carries none) — every XLA OOM phrasing spells the request
    near an 'allocat*' verb with a unit suffix."""
    m = _SIZE_RE.search(str(msg) or "")
    if not m:
        return 0
    try:
        scale = _UNITS.get(m.group(2).lower().rstrip("s") + (
            "s" if m.group(2).lower() in ("bytes",) else ""), None)
        if scale is None:
            scale = _UNITS.get(m.group(2).lower(), 1)
        return int(float(m.group(1)) * scale)  # hot-sync-ok: parsing the XLA error string, not a device read
    except (TypeError, ValueError):
        return 0


def mem_state():
    """The debug-bundle payload (`mem_state.json`): the attribution
    report, the full per-tag ledger, per-pool pool_stats for every
    registered pool owner that exposes one, the compile ledger's
    per-executable memory_analysis peaks, per-tag peak bytes, the
    recent memory-record tail, and — when an OOM routed through
    `oom_error` — the parsed request context. Never raises."""
    pools = {}
    for tag, entry in _snapshot_entries():
        if entry.owner is None:
            continue
        owner = entry.owner()
        if owner is None or not hasattr(owner, "pool_stats"):
            continue
        try:
            pools[tag] = owner.pool_stats()
        except Exception:
            pools[tag] = {"error": "pool_stats refused"}
    exec_peaks = {}
    try:
        from . import compile_observatory as _cobs
        for r in _cobs.ledger():
            p = float(r.get("peak_memory_bytes", 0.0) or 0.0)  # hot-sync-ok: host dict field from the compile ledger, not a device read
            t = r.get("tag", "?")
            if p > exec_peaks.get(t, 0.0):
                exec_peaks[t] = p
    except Exception:
        pass
    return {
        "report": mem_report(),
        "ledger": ledger(),
        "pools": pools,
        "executable_peaks": exec_peaks,
        "tag_peaks": dict(_state["peaks"]),
        "records_tail": records_tail(),
        "last_oom": _state["last_oom"],
    }


def oom_error(exc, site):
    """Forensics for one allocator exhaustion: stamp the OOM context
    (site + requested bytes parsed from the XLA message), flight-record
    a ``device_oom`` event, dump a debug bundle (whose
    ``mem_state.json`` carries the full ledger), and return a
    `DeviceOOMError` naming the top-3 holders — the caller raises it
    `from` the original. Never raises on its own forensics."""
    requested = parse_requested_bytes(exc)
    top = []
    try:
        led = ledger()
        top = sorted(((t, v["bytes"]) for t, v in led.items()),
                     key=lambda kv: -kv[1])[:3]
    except Exception:
        pass
    _state["last_oom"] = {
        "site": str(site),
        "requested_bytes": int(requested),
        "error": f"{type(exc).__name__}: {exc}"[:500],
        "top_holders": [[t, int(b)] for t, b in top],
    }
    _ensure_state_provider()
    try:
        _fr.record_event(
            "device_oom", site=str(site),
            requested_bytes=int(requested),
            top_holders=[f"{t}={b}" for t, b in top],
            error=str(exc)[:300])
    except Exception:
        pass
    bundle = None
    try:
        bundle = _fr.dump("oom", exc=exc)
    except Exception:
        pass
    holders = ", ".join(f"{t}={b / 2**20:.1f}MiB" for t, b in top) \
        or "ledger empty"
    req = f" (requested {requested} bytes)" if requested else ""
    msg = (f"device out of memory at {site}{req}; top holders: "
           f"{holders}"
           + (f"; debug bundle: {bundle}" if bundle else ""))
    return DeviceOOMError(msg, site=site, requested_bytes=requested,
                          top_holders=top, bundle_dir=bundle)


def _ensure_state_provider():
    """Register `mem_state` with the flight recorder exactly once
    (module-level function: the recorder holds it strongly, which is
    correct — the module outlives every registrant)."""
    with _lock:
        if _state_registered[0]:
            return
        _state_registered[0] = True
    try:
        _fr.register_state_provider("mem_state", mem_state)
    except Exception:
        pass


def reset():
    """Drop the tag registry, record ring, peaks, cadence marks, and
    OOM context (tests)."""
    with _lock:
        _tags.clear()
        _records.clear()
        _state["emitted"] = set()
        _state["peaks"] = {}
        _state["last_oom"] = None
