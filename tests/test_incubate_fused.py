"""incubate.nn fused transformer layers. Parity:
python/paddle/incubate/nn/layer/fused_transformer.py — same layer
semantics (attention/FFN with residual + layer norm folded in), fused on
TPU via flash attention + Pallas layer norm.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.incubate as incubate


def _mha(**kw):
    paddle.seed(0)
    m = incubate.nn.FusedMultiHeadAttention(
        64, 4, dropout_rate=0.0, attn_dropout_rate=0.0, **kw)
    m.eval()
    return m


class TestFusedMultiHeadAttention:
    def test_post_ln_output_is_normalized(self):
        m = _mha()
        out = m(paddle.randn([2, 8, 64])).numpy()
        assert out.shape == (2, 8, 64)
        assert abs(out.mean()) < 0.1 and abs(out.std() - 1.0) < 0.2

    def test_pre_ln_keeps_residual_scale(self):
        m = _mha(normalize_before=True)
        x = paddle.randn([2, 8, 64])
        out = m(x)
        assert out.shape == x.shape
        # pre-norm: out = x + attn(ln(x)) — correlated with input
        a, b = out.numpy().ravel(), x.numpy().ravel()
        assert np.corrcoef(a, b)[0, 1] > 0.5

    def test_matches_unfused_composition(self):
        m = _mha(normalize_before=True)
        x = paddle.randn([2, 8, 64])
        from paddle_tpu.nn import functional as F
        h = m.ln(x)
        B, T, E = h.shape
        qkv = m.qkv_proj(h).reshape([B, T, 3, 4, 16])
        q, k, v = qkv.unbind(axis=2)
        ref = x + m.out_proj(
            F.scaled_dot_product_attention(q, k, v).reshape([B, T, E]))
        np.testing.assert_allclose(m(x).numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)


class TestFusedFeedForward:
    def test_forward_and_grad(self):
        paddle.seed(1)
        ff = incubate.nn.FusedFeedForward(32, 64, dropout_rate=0.0,
                                          activation="gelu")
        x = paddle.randn([4, 6, 32])
        out = ff(x)
        assert out.shape == x.shape
        out.sum().backward()
        assert ff.linear1.weight.grad is not None

    def test_matches_unfused_composition(self):
        paddle.seed(2)
        from paddle_tpu.nn import functional as F
        ff = incubate.nn.FusedFeedForward(32, 64, dropout_rate=0.0,
                                          activation="relu")
        ff.eval()
        x = paddle.randn([2, 4, 32])
        ref = ff.ln(x + ff.linear2(F.relu(ff.linear1(x))))
        np.testing.assert_allclose(ff(x).numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)
