"""The approx (approx_max_k subset) sampling path must agree with the
exact path — exercised on CPU via PADDLE_TPU_APPROX_SAMPLING=1."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig

pytestmark = [pytest.mark.slow, pytest.mark.heavy]  # multi-minute: out of tier-1 and the quick gate


def _gen(approx, top_k=None, top_p=None, vocab=16384, temperature=1.0):
    os.environ["PADDLE_TPU_APPROX_SAMPLING"] = "1" if approx else "0"
    try:
        cfg = GPTConfig(vocab_size=vocab, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=32,
                        dropout=0.0)
        paddle.seed(7)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(np.array([[5, 9, 2]], np.int64))
        paddle.seed(123)  # same RNG stream for both runs
        out = m.generate(ids, max_new_tokens=8, top_k=top_k, top_p=top_p,
                         temperature=temperature)
        return np.asarray(out.value)
    finally:
        del os.environ["PADDLE_TPU_APPROX_SAMPLING"]


# top_p alone uses temperature 0.2: a random-init model is near-uniform
# over 16k tokens, whose nucleus exceeds the 4096-token subset — the
# approx path then (by design) keeps everything instead of truncating;
# sharpened logits put the nucleus inside the subset, where the two
# paths must agree exactly
@pytest.mark.parametrize("top_k,top_p,temp", [(50, None, 1.0),
                                              (None, 0.9, 0.2),
                                              (50, 0.9, 1.0)])
def test_approx_matches_exact(top_k, top_p, temp):
    # identical weights + identical keys: the sampled ids must match
    # token-for-token when the threshold lives inside the subset
    exact = _gen(False, top_k, top_p, temperature=temp)
    approx = _gen(True, top_k, top_p, temperature=temp)
    np.testing.assert_array_equal(exact, approx)


def test_uniform_nucleus_falls_back_to_keep_all():
    # nucleus wider than the subset: approx path must not truncate at
    # the subset edge — it keeps the full distribution (still a valid
    # sample, just unfiltered) instead of biasing toward the head
    out = _gen(True, top_k=None, top_p=0.95)
    assert out.shape == (1, 11)


def test_large_top_k_falls_back_to_exact():
    # top_k > subset size must still mask correctly (exact kth used)
    out = _gen(True, top_k=8192, top_p=None)
    assert out.shape == (1, 11)
