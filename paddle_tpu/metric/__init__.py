"""paddle.metric. Parity: python/paddle/metric/metrics.py."""
import numpy as np

from ..framework.core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = _np(pred)
        l = _np(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l.squeeze(-1)
        idx = np.argsort(-p, axis=-1)[..., :self.maxk]
        correct = idx == l[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        res = []
        for i, k in enumerate(self.topk):
            hits = c[..., :k].sum()
            self.total[i] += hits
            self.count[i] += num
            res.append(hits / max(num, 1))
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1)
        bins = np.round(p * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        auc = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            auc += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    p = _np(input)
    l = _np(label).reshape(-1)
    idx = np.argsort(-p, axis=-1)[:, :k]
    hits = (idx == l[:, None]).any(axis=1).mean()
    return Tensor(np.asarray(hits, np.float32))
